"""openCypher recursive-descent parser.

Grammar shape follows the openCypher specification (the reference parses
with ANTLR against frontend/opencypher/grammar/Cypher.g4 plus extensions in
MemgraphCypher.g4); this is a fresh hand-written implementation covering the
query surface the engine executes: reading/writing clauses, expressions with
full precedence, patterns incl. variable-length edges, CALL ... YIELD,
UNION, DDL (indexes/constraints), transactions, EXPLAIN/PROFILE, and the
admin/info query families.
"""

from __future__ import annotations

from typing import Optional

from ...exceptions import SyntaxException
from . import ast as A
from .lexer import T, Token, tokenize


def parse(text: str):
    """Parse one statement (trailing ';' tolerated). Returns an AST root:
    CypherQuery | IndexQuery | ConstraintQuery | InfoQuery | ... """
    p = Parser(tokenize(text))
    p._source = text
    return p.parse_statement()


class Parser:
    def __init__(self, tokens: list[Token]) -> None:
        self.toks = tokens
        self.i = 0
        self._source: str | None = None  # original text (verbatim columns)

    # --- token helpers ------------------------------------------------------

    @property
    def cur(self) -> Token:
        return self.toks[self.i]

    def peek(self, k=1) -> Token:
        return self.toks[min(self.i + k, len(self.toks) - 1)]

    def advance(self) -> Token:
        tok = self.toks[self.i]
        if tok.type != T.EOF:
            self.i += 1
        return tok

    def at(self, type_: str) -> bool:
        return self.cur.type == type_

    def at_kw(self, *names: str) -> bool:
        return self.cur.is_kw(*names)

    def _at_profile_word(self) -> bool:
        """PROFILE/PROFILES at the cursor (keyword or identifier)."""
        return self.cur.is_kw("PROFILE") or (
            self.cur.type == T.IDENT
            and self.cur.value.upper() in ("PROFILE", "PROFILES"))

    def _peek_is_profile(self) -> bool:
        nxt = self.peek()
        return nxt.is_kw("PROFILE") or (
            nxt.type == T.IDENT and nxt.value.upper() == "PROFILE")

    def accept(self, type_: str) -> Optional[Token]:
        if self.cur.type == type_:
            return self.advance()
        return None

    def accept_kw(self, *names: str) -> Optional[Token]:
        if self.cur.is_kw(*names):
            return self.advance()
        return None

    def expect(self, type_: str) -> Token:
        if self.cur.type != type_:
            self.error(f"expected {type_!r}, got {self._desc(self.cur)}")
        return self.advance()

    def expect_kw(self, *names: str) -> Token:
        if not self.cur.is_kw(*names):
            self.error(f"expected {'/'.join(names)}, got {self._desc(self.cur)}")
        return self.advance()

    @staticmethod
    def _desc(tok: Token) -> str:
        if tok.type == T.EOF:
            return "end of input"
        return repr(tok.value if tok.value is not None else tok.type)

    def error(self, msg: str):
        tok = self.cur
        raise SyntaxException(f"line {tok.line}:{tok.col} {msg}")

    def name_token(self) -> str:
        """Identifier or any keyword used as a name (Cypher allows both;
        keywords keep their ORIGINAL case — `:User` must intern "User",
        not "user", even though USER is a keyword)."""
        if self.at(T.IDENT):
            return self.advance().value
        if self.cur.type == T.KEYWORD:
            tok = self.advance()
            return tok.raw if tok.raw is not None else tok.value.lower()
        self.error(f"expected a name, got {self._desc(self.cur)}")

    # --- statement dispatch -------------------------------------------------

    def parse_statement(self):
        explain = profile = False
        if self.accept_kw("EXPLAIN"):
            explain = True
        elif self.accept_kw("PROFILE"):
            profile = True

        node = self._dispatch()
        if isinstance(node, A.CypherQuery):
            node.explain = explain
            node.profile = profile
        elif explain or profile:
            self.error("EXPLAIN/PROFILE is only supported for Cypher queries")
        self.accept(";")
        if not self.at(T.EOF):
            self.error(f"unexpected input after statement: {self._desc(self.cur)}")
        return node

    def _dispatch(self):
        if self.at_kw("USE"):
            self.advance()
            self.accept_kw("DATABASE")
            return A.MultiDatabaseQuery("use", name=self.name_token())
        if self.at(T.IDENT) and self.cur.value.upper() in ("SUSPEND",
                                                          "RESUME"):
            # hot/cold tenants (reference: specs/hot-cold-databases.md)
            action = self.advance().value.lower()
            self.expect_kw("DATABASE")
            return A.MultiDatabaseQuery(action, name=self.name_token())
        if self.at(T.IDENT) and self.cur.value.upper() == "CLEAR" and \
                self.peek().type == T.IDENT and \
                self.peek().value.upper() == "TENANT":
            self.advance()
            return self.parse_tenant_profile("clear")
        if self.at(T.IDENT) and self.cur.value.upper() == "CLEAR" and \
                self._peek_is_profile():
            # CLEAR PROFILE FOR user (MemgraphCypher.g4:981)
            self.advance(); self.advance()
            self.expect_kw("FOR")
            return A.UserProfileQuery("clear", user=self.name_token())
        if self.at(T.IDENT) and self.cur.value.upper() == "UPDATE" and \
                self._peek_is_profile():
            # UPDATE PROFILE p LIMIT k v, ... (MemgraphCypher.g4:974)
            self.advance(); self.advance()
            name = self.name_token()
            limits = {}
            if self.accept_kw("LIMIT"):
                limits = self.parse_limit_list()
            return A.UserProfileQuery("update", name=name, limits=limits)
        if self.at(T.IDENT) and self.cur.value.upper() == "ALTER" and \
                self.peek().type == T.IDENT and \
                self.peek().value.upper() == "TENANT":
            self.advance()
            return self.parse_tenant_profile("alter")
        if self.at_kw("CREATE"):
            nxt = self.peek()
            if nxt.type == T.IDENT and nxt.value.upper() == "TENANT":
                self.advance()
                return self.parse_tenant_profile("create")
            if self._peek_is_profile():
                # CREATE PROFILE p [LIMIT k v, ...]
                self.advance(); self.advance()
                name = self.name_token()
                limits = {}
                if self.accept_kw("LIMIT"):
                    limits = self.parse_limit_list()
                return A.UserProfileQuery("create", name=name,
                                          limits=limits)
            if nxt.is_kw("DATABASE"):
                self.advance(); self.advance()
                return A.MultiDatabaseQuery("create", name=self.name_token())
            if nxt.type == T.IDENT and nxt.value.upper() in (
                    "KAFKA", "PULSAR", "FILE") and \
                    self.peek(2).is_kw("STREAM"):
                return self.parse_create_stream()
            if nxt.is_kw("STREAM"):
                return self.parse_create_stream()
            if nxt.is_kw("INDEX"):
                return self.parse_create_index()
            if nxt.is_kw("EDGE"):
                return self.parse_create_edge_index()
            if nxt.is_kw("CONSTRAINT"):
                return self.parse_constraint("create")
            if nxt.is_kw("SNAPSHOT"):
                self.advance(); self.advance()
                return A.SnapshotQuery("create")
            if nxt.is_kw("TRIGGER"):
                return self.parse_create_trigger()
            if nxt.is_kw("USER"):
                return self.parse_auth()
            if nxt.is_kw("ROLE"):
                self.advance(); self.advance()
                return A.AuthQuery("create_role", role=self.name_token())
            if nxt.type == "IDENT" and str(nxt.value).upper() == "ENUM":
                self.advance(); self.advance()
                name = self.name_token()
                if not (self.at(T.IDENT)
                        and self.cur.value.upper() == "VALUES"):
                    self.error("expected VALUES in CREATE ENUM")
                self.advance()
                self.expect("{")
                values = [self.name_token()]
                while self.accept(","):
                    values.append(self.name_token())
                self.expect("}")
                return A.EnumQuery("create", name, values)
            return self.parse_cypher_query()
        if self.at_kw("DROP"):
            nxt = self.peek()
            if nxt.type == T.IDENT and nxt.value.upper() == "TENANT":
                self.advance()
                return self.parse_tenant_profile("drop")
            if self._peek_is_profile():
                self.advance(); self.advance()
                return A.UserProfileQuery("drop", name=self.name_token())
            if nxt.is_kw("INDEX"):
                return self.parse_drop_index()
            if nxt.is_kw("EDGE"):
                return self.parse_drop_edge_index()
            if nxt.is_kw("CONSTRAINT"):
                return self.parse_constraint("drop")
            if nxt.is_kw("TRIGGER"):
                self.advance(); self.advance()
                return A.TriggerQuery("drop", name=self.name_token())
            if nxt.is_kw("REPLICA"):
                self.advance(); self.advance()
                return A.ReplicationQuery("drop", name=self.name_token())
            if nxt.is_kw("STREAM"):
                self.advance(); self.advance()
                return A.StreamQuery("drop", name=self.name_token())
            if nxt.is_kw("DATABASE"):
                self.advance(); self.advance()
                return A.MultiDatabaseQuery("drop", name=self.name_token())
            if nxt.is_kw("USER"):
                return self.parse_auth()
            if nxt.is_kw("ROLE"):
                self.advance(); self.advance()
                return A.AuthQuery("drop_role", role=self.name_token())
            self.error("unsupported DROP statement")
        if self.at_kw("SHOW"):
            return self.parse_show()
        if self.at_kw("BEGIN"):
            self.advance()
            return A.TransactionQuery("begin")
        if self.at_kw("COMMIT"):
            self.advance()
            return A.TransactionQuery("commit")
        if self.at_kw("ROLLBACK"):
            self.advance()
            return A.TransactionQuery("rollback")
        if self.at_kw("TERMINATE"):
            self.advance()
            self.expect_kw("TRANSACTIONS")
            ids = [self.parse_expression()]
            while self.accept(","):
                ids.append(self.parse_expression())
            return A.TerminateTransactionsQuery(ids)
        if self.at_kw("RECOVER"):
            self.advance()
            self.expect_kw("SNAPSHOT")
            if self.accept_kw("FROM"):
                # remote/explicit source: file path, http(s):// or s3://
                # (reference: storage.hpp:158-168 remote snapshot load)
                return A.SnapshotQuery("recover",
                                       source=self.expect(T.STRING).value)
            return A.SnapshotQuery("recover")
        if self.at_kw("DUMP"):
            self.advance()
            self.expect_kw("DATABASE")
            return A.DumpQuery()
        if self.at_kw("ANALYZE"):
            self.advance()
            self.expect_kw("GRAPH")
            labels = []
            if self.accept_kw("ON"):
                self.expect_kw("LABELS")
                if not self.accept("*"):   # * = all labels (grammar:636)
                    labels.append(self._colon_label())
                    while self.accept(","):
                        labels.append(self._colon_label())
            action = "analyze"
            if self.accept_kw("DELETE"):
                if self.at_kw("STATS") or (
                        self.at(T.IDENT)
                        and self.cur.value.upper() == "STATISTICS"):
                    self.advance()
                else:
                    self.error("expected STATISTICS after DELETE")
                action = "delete"
            return A.AnalyzeGraphQuery(action, labels)
        if self.at_kw("SET"):
            nxt = self.peek()
            if nxt.type == T.IDENT and nxt.value.upper() == "INSTANCE":
                self.advance(); self.advance()
                name = self.name_token()
                self.expect_kw("TO")
                self.expect_kw("MAIN")
                return A.CoordinatorQuery("set_main", name=name)
            if nxt.type == T.IDENT and nxt.value.upper() == "TENANT":
                self.advance()
                return self.parse_tenant_profile("assign")
            if self._peek_is_profile():
                # SET PROFILE FOR user TO profile
                self.advance(); self.advance()
                self.expect_kw("FOR")
                user = self.name_token()
                self.expect_kw("TO")
                return A.UserProfileQuery("assign", user=user,
                                          name=self.name_token())
            if nxt.is_kw("GLOBAL", "SESSION", "NEXT"):
                return self.parse_isolation_or_storage()
            if nxt.is_kw("STORAGE"):
                return self.parse_isolation_or_storage()
            if nxt.is_kw("REPLICATION"):
                return self.parse_set_replication_role()
            if nxt.is_kw("DATABASE"):
                self.advance(); self.advance()
                if not (self.at(T.IDENT)
                        and self.cur.value.upper() == "SETTING"):
                    self.error("expected SETTING after SET DATABASE")
                self.advance()
                name = self.expect(T.STRING).value
                self.expect_kw("TO")
                value = self.expect(T.STRING).value
                return A.SettingQuery("set", name, value)
            if nxt.is_kw("PASSWORD"):
                return self.parse_auth()
            if nxt.is_kw("ROLE"):
                self.advance(); self.advance()
                self.expect_kw("FOR")
                user = self.name_token()
                self.expect_kw("TO")
                return A.AuthQuery("set_role", user=user,
                                   role=self.name_token())
            return self.parse_cypher_query()
        if self.at(T.IDENT) and self.cur.value.upper() == "ALTER" and \
                self.peek().type == T.IDENT and \
                str(self.peek().value).upper() == "ENUM":
            self.advance(); self.advance()
            name = self.name_token()
            if not (self.at(T.IDENT) and self.cur.value.upper() == "ADD"):
                self.error("expected ADD VALUE in ALTER ENUM")
            self.advance()
            if not (self.at(T.IDENT) and self.cur.value.upper() == "VALUE"):
                self.error("expected VALUE after ADD")
            self.advance()
            return A.EnumQuery("add_value", name, [self.name_token()])
        if self.at_kw("GRANT") or self.at_kw("DENY"):
            action = self.advance().value.lower()
            first = self.name_token().upper()
            # fine-grained: GRANT <LEVEL> ON LABELS :a, :b | * TO name
            # (reference grammar: MemgraphCypher.g4 grantPrivilege with
            # READ/UPDATE/CREATE_DELETE/NOTHING ON LABELS/EDGE_TYPES)
            if first in ("READ", "UPDATE", "CREATE_DELETE", "NOTHING") \
                    and self.at_kw("ON"):
                self.advance()
                kind_tok = self.name_token().upper()
                if kind_tok not in ("LABELS", "EDGE_TYPES"):
                    self.error("expected LABELS or EDGE_TYPES")
                items = self.parse_fg_items()
                self.expect_kw("TO")
                target = self.name_token()
                level = "NOTHING" if action == "deny" else first
                return A.AuthQuery("grant_fine_grained", user=target,
                                   fg_kind=kind_tok.lower(),
                                   fg_items=items, fg_level=level)
            privs = [first]
            if privs == ["ALL"]:
                self.accept_kw("PRIVILEGES")
            while self.accept(","):
                privs.append(self.name_token().upper())
            self.expect_kw("TO")
            target = self.name_token()
            return A.AuthQuery(action, user=target, privileges=privs)
        if self.at_kw("REVOKE"):
            self.advance()
            first = self.name_token().upper()
            if first in ("READ", "UPDATE", "CREATE_DELETE", "NOTHING") \
                    and self.at_kw("ON"):
                self.advance()
                kind_tok = self.name_token().upper()
                if kind_tok not in ("LABELS", "EDGE_TYPES"):
                    self.error("expected LABELS or EDGE_TYPES")
                items = self.parse_fg_items()
                self.expect_kw("FROM")
                target = self.name_token()
                return A.AuthQuery("revoke_fine_grained", user=target,
                                   fg_kind=kind_tok.lower(), fg_items=items)
            privs = [first]
            if privs == ["ALL"]:
                self.accept_kw("PRIVILEGES")
            while self.accept(","):
                privs.append(self.name_token().upper())
            self.expect_kw("FROM")
            target = self.name_token()
            return A.AuthQuery("revoke", user=target, privileges=privs)
        if self.at_kw("REGISTER"):
            if self.peek().type == T.IDENT and \
                    self.peek().value.upper() == "INSTANCE":
                return self.parse_register_instance()
            return self.parse_register_replica()
        if self.at(T.IDENT) and self.cur.value.upper() == "UNREGISTER":
            self.advance()
            if not (self.at(T.IDENT)
                    and self.cur.value.upper() == "INSTANCE"):
                self.error("expected INSTANCE")
            self.advance()
            return A.CoordinatorQuery("unregister", name=self.name_token())
        if self.at_kw("START"):
            self.advance()
            if self.accept_kw("ALL"):
                self.expect_kw("STREAMS")
                return A.StreamQuery("start_all")
            self.expect_kw("STREAM")
            return A.StreamQuery("start", name=self.name_token())
        if self.at_kw("STOP"):
            self.advance()
            if self.accept_kw("ALL"):
                self.expect_kw("STREAMS")
                return A.StreamQuery("stop_all")
            self.expect_kw("STREAM")
            return A.StreamQuery("stop", name=self.name_token())
        if self.at_kw("CHECK"):
            self.advance()
            self.expect_kw("STREAM")
            return A.StreamQuery("check", name=self.name_token())
        if self.at_kw("FREE"):
            self.advance()
            self.expect_kw("MEMORY")
            return A.InfoQuery("free_memory")
        if self.at_kw("SESSION") and self.peek().type == T.IDENT and \
                self.peek().value.upper() == "TRACE":
            self.advance()
            self.advance()
            if self.accept_kw("ON"):
                return A.SessionTraceQuery(True)
            if self.at(T.IDENT) and self.cur.value.upper() == "OFF":
                self.advance()
                return A.SessionTraceQuery(False)
            self.error("expected ON or OFF after SESSION TRACE")
        if self.at_kw("ENABLE"):
            self.advance()
            self.expect_kw("TTL")
            period = None
            if self.accept_kw("EVERY"):
                period = self.expect(T.STRING).value
            return A.TtlQuery("enable", period)
        if self.at_kw("DISABLE"):
            self.advance()
            self.expect_kw("TTL")
            return A.TtlQuery("disable")
        return self.parse_cypher_query()

    def _colon_label(self) -> str:
        self.expect(":")
        return self.name_token()

    # --- DDL ---------------------------------------------------------------

    def parse_create_index(self) -> A.IndexQuery:
        self.expect_kw("CREATE")
        self.expect_kw("INDEX")
        self.expect_kw("ON")
        label = self._colon_label()
        props: list[str] = []
        if self.accept("("):
            props.append(self.name_token())
            while self.accept(","):
                props.append(self.name_token())
            self.expect(")")
        kind = "label_property" if props else "label"
        return A.IndexQuery("create", kind, label, props)

    def parse_drop_index(self) -> A.IndexQuery:
        self.expect_kw("DROP")
        self.expect_kw("INDEX")
        self.expect_kw("ON")
        label = self._colon_label()
        props: list[str] = []
        if self.accept("("):
            props.append(self.name_token())
            while self.accept(","):
                props.append(self.name_token())
            self.expect(")")
        kind = "label_property" if props else "label"
        return A.IndexQuery("drop", kind, label, props)

    def parse_create_edge_index(self) -> A.IndexQuery:
        self.expect_kw("CREATE")
        self.expect_kw("EDGE")
        self.expect_kw("INDEX")
        self.expect_kw("ON")
        self.expect(":")
        etype = self.name_token()
        return A.IndexQuery("create", "edge_type", None, [], etype)

    def parse_drop_edge_index(self) -> A.IndexQuery:
        self.expect_kw("DROP")
        self.expect_kw("EDGE")
        self.expect_kw("INDEX")
        self.expect_kw("ON")
        self.expect(":")
        etype = self.name_token()
        return A.IndexQuery("drop", "edge_type", None, [], etype)

    def parse_constraint(self, action: str) -> A.ConstraintQuery:
        self.advance()  # CREATE/DROP
        self.expect_kw("CONSTRAINT")
        self.expect_kw("ON")
        self.expect("(")
        var = self.name_token()
        self.expect(":")
        label = self.name_token()
        self.expect(")")
        self.expect_kw("ASSERT")
        if self.accept_kw("EXISTS"):
            self.expect("(")
            self._qualified_prop(var)
            prop = self._last_prop
            self.expect(")")
            return A.ConstraintQuery(action, "exists", label, [prop])
        # n.a IS UNIQUE / n.a, n.b IS UNIQUE / n.a IS TYPED STRING
        props = [self._qualified_prop(var)]
        while self.accept(","):
            props.append(self._qualified_prop(var))
        self.expect_kw("IS")
        if self.accept_kw("UNIQUE"):
            return A.ConstraintQuery(action, "unique", label, props)
        self.expect_kw("TYPED")
        type_name = self.name_token()
        return A.ConstraintQuery(action, "type", label, props, type_name)

    _last_prop: str = ""

    def _qualified_prop(self, var: str) -> str:
        name = self.name_token()
        if name != var:
            self.error(f"unknown variable {name!r} in constraint")
        self.expect(".")
        self._last_prop = self.name_token()
        return self._last_prop

    def parse_show(self):
        self.expect_kw("SHOW")
        if self.accept_kw("INDEX"):
            self.expect_kw("INFO")
            return A.InfoQuery("index")
        if self.accept_kw("CONSTRAINT"):
            self.expect_kw("INFO")
            return A.InfoQuery("constraint")
        if self.accept_kw("STORAGE"):
            self.expect_kw("INFO")
            return A.InfoQuery("storage")
        if self.accept_kw("BUILD"):
            self.expect_kw("INFO")
            return A.InfoQuery("build")
        if self.accept_kw("METRICS"):
            self.accept_kw("INFO")
            return A.InfoQuery("metrics")
        if self.accept_kw("QUERY"):
            # SHOW QUERY STATS (r14, mgstat): bounded top-K fingerprint
            # statistics from observability/stats.py
            self.expect_kw("STATS")
            return A.InfoQuery("query_stats")
        if self.at(T.IDENT) and self.cur.value.upper() == "LICENSE":
            self.advance()
            self.expect_kw("INFO")
            return A.InfoQuery("license")
        if self.at(T.IDENT) and self.cur.value.upper() == "ACTIVE":
            # SHOW ACTIVE USERS INFO (reference: MemgraphCypher.g4:1032
            # systemInfoQuery activeUsersInfo)
            self.advance()
            if not (self.at(T.IDENT) and self.cur.value.upper() == "USERS"):
                self.error("expected USERS after SHOW ACTIVE")
            self.advance()
            self.expect_kw("INFO")
            return A.InfoQuery("active_users")
        if self.accept_kw("TRANSACTIONS"):
            return A.ShowTransactionsQuery()
        if self.accept_kw("SNAPSHOT"):  # SHOW SNAPSHOTS
            return A.SnapshotQuery("show")
        if self.accept_kw("TRIGGERS"):
            return A.TriggerQuery("show")
        if self.accept_kw("DATABASES"):
            return A.MultiDatabaseQuery("show")
        if self.accept_kw("DATABASE"):
            if self.at(T.IDENT) and self.cur.value.upper() == "SETTINGS":
                self.advance()
                return A.SettingQuery("show_all")
            if self.at(T.IDENT) and self.cur.value.upper() == "SETTING":
                self.advance()
                return A.SettingQuery("show_one",
                                      self.expect(T.STRING).value)
            return A.InfoQuery("database")
        if self.accept_kw("SCHEMA"):
            self.expect_kw("INFO")
            return A.InfoQuery("schema")
        if self.accept_kw("REPLICAS"):
            return A.ReplicationQuery("show_replicas")
        if self.accept_kw("REPLICATION"):
            self.expect_kw("ROLE")
            return A.ReplicationQuery("show_role")
        if self.accept_kw("STREAMS"):
            return A.StreamQuery("show")
        if self.at(T.IDENT) and self.cur.value.upper() == "USERS":
            self.advance()
            if self.at_kw("FOR"):
                # SHOW USERS FOR PROFILE p (MemgraphCypher.g4:979)
                self.advance()
                if not self._at_profile_word():
                    self.error("expected PROFILE after SHOW USERS FOR")
                self.advance()
                return A.UserProfileQuery("users_for",
                                          name=self.name_token())
            return A.AuthQuery("show_users")
        if self._at_profile_word():
            plural = self.cur.value.upper() == "PROFILES"
            self.advance()
            if plural:
                return A.UserProfileQuery("show")
            if self.accept_kw("FOR"):
                return A.UserProfileQuery("show_for",
                                          user=self.name_token())
            return A.UserProfileQuery("show", name=self.name_token())
        if self.at(T.IDENT) and self.cur.value.upper() == "TENANT":
            self.advance()
            if not (self.at_kw("PROFILE") or (
                    self.at(T.IDENT) and self.cur.value.upper()
                    in ("PROFILE", "PROFILES"))):
                self.error("expected PROFILE(S) after SHOW TENANT")
            plural = self.advance().value.upper() == "PROFILES"
            name = None if plural else self.name_token()
            return A.TenantProfileQuery("show", name=name)
        if self.at(T.IDENT) and self.cur.value.upper() == "CURRENT":
            self.advance()
            if self.at_kw("USER") or (self.at(T.IDENT)
                                      and self.cur.value.upper() == "USER"):
                self.advance()
                return A.AuthQuery("show_current_user")
            self.error("expected USER after SHOW CURRENT")
        if self.at(T.IDENT) and self.cur.value.upper() == "ROLES":
            self.advance()
            return A.AuthQuery("show_roles")
        if self.accept_kw("PRIVILEGES"):
            self.expect_kw("FOR")
            return A.AuthQuery("show_privileges", user=self.name_token())
        if self.accept_kw("VERSION"):
            return A.InfoQuery("version")
        if self.at(T.IDENT) and self.cur.value.upper() == "ENUMS":
            self.advance()
            return A.EnumQuery("show")
        if self.at(T.IDENT) and self.cur.value.upper() == "INSTANCES":
            self.advance()
            return A.CoordinatorQuery("show")
        self.error("unsupported SHOW statement")

    def parse_register_instance(self) -> A.CoordinatorQuery:
        self.expect_kw("REGISTER")
        self.advance()  # INSTANCE
        name = self.name_token()
        self.expect_kw("ON")
        mgmt = self.expect(T.STRING).value
        self.expect_kw("WITH")
        repl = self.expect(T.STRING).value
        bolt = None
        # optional bolt endpoint so coordinators can serve ROUTE tables
        # (reference: REGISTER INSTANCE ... WITH CONFIG {"bolt_server": ...})
        if self.at(T.IDENT) and self.cur.value.upper() == "BOLT":
            self.advance()
            bolt = self.expect(T.STRING).value
        return A.CoordinatorQuery("register", name=name, mgmt_address=mgmt,
                                  replication_address=repl,
                                  bolt_address=bolt)

    def parse_create_stream(self) -> A.StreamQuery:
        self.expect_kw("CREATE")
        kind = "kafka"
        if self.at(T.IDENT) and self.cur.value.upper() in (
                "KAFKA", "PULSAR", "FILE"):
            kind = self.advance().value.lower()
        self.expect_kw("STREAM")
        name = self.name_token()
        q = A.StreamQuery("create", name=name, kind=kind)
        while True:
            if self.accept_kw("TOPICS"):
                if self.at(T.STRING):
                    q.topics.append(self.advance().value)
                else:
                    q.topics.append(self.name_token())
                while self.accept(","):
                    if self.at(T.STRING):
                        q.topics.append(self.advance().value)
                    else:
                        q.topics.append(self.name_token())
                continue
            if self.accept_kw("TRANSFORM"):
                parts = [self.name_token()]
                while self.accept("."):
                    parts.append(self.name_token())
                q.transform = ".".join(parts)
                continue
            if self.accept_kw("BATCH_SIZE"):
                q.batch_size = self.expect(T.INT).value
                continue
            if self.accept_kw("BATCH_INTERVAL"):
                q.batch_interval_ms = self.expect(T.INT).value
                continue
            if self.accept_kw("BOOTSTRAP_SERVERS"):
                q.bootstrap_servers = self.expect(T.STRING).value
                continue
            if self.accept_kw("SERVICE_URL"):
                q.service_url = self.expect(T.STRING).value
                continue
            if self.accept_kw("CONSUMER_GROUP"):
                q.consumer_group = self.expect(T.STRING).value
                continue
            break
        if not q.topics or not q.transform:
            self.error("CREATE STREAM requires TOPICS and TRANSFORM")
        return q

    def parse_set_replication_role(self) -> A.ReplicationQuery:
        self.expect_kw("SET")
        self.expect_kw("REPLICATION")
        self.expect_kw("ROLE")
        self.expect_kw("TO")
        if self.accept_kw("MAIN"):
            return A.ReplicationQuery("set_role_main")
        self.expect_kw("REPLICA")
        port = 10000
        if self.accept_kw("WITH"):
            self.expect_kw("PORT")
            port = self.expect(T.INT).value
        return A.ReplicationQuery("set_role_replica", port=port)

    def parse_register_replica(self) -> A.ReplicationQuery:
        self.expect_kw("REGISTER")
        self.expect_kw("REPLICA")
        name = self.name_token()
        mode = "SYNC"
        if self.accept_kw("SYNC"):
            mode = "SYNC"
        elif self.accept_kw("ASYNC"):
            mode = "ASYNC"
        elif self.accept_kw("STRICT_SYNC"):
            mode = "STRICT_SYNC"
        self.expect_kw("TO")
        addr = self.expect(T.STRING).value
        return A.ReplicationQuery("register", name=name, mode=mode,
                                  address=addr)

    def parse_isolation_or_storage(self):
        self.expect_kw("SET")
        if self.accept_kw("STORAGE"):
            self.expect_kw("MODE")
            if self.accept_kw("IN_MEMORY_ANALYTICAL"):
                return A.StorageModeQuery("IN_MEMORY_ANALYTICAL")
            tok = self.advance()
            mode = str(tok.value).upper()
            if mode == "ANALYTICAL":
                mode = "IN_MEMORY_ANALYTICAL"
            elif mode == "TRANSACTIONAL":
                mode = "IN_MEMORY_TRANSACTIONAL"
            return A.StorageModeQuery(mode)
        scope_tok = self.expect_kw("GLOBAL", "SESSION", "NEXT")
        scope = scope_tok.value.lower()
        self.expect_kw("TRANSACTION")
        self.expect_kw("ISOLATION")
        self.expect_kw("LEVEL")
        if self.accept_kw("SNAPSHOT"):
            self.expect_kw("ISOLATION")
            return A.IsolationLevelQuery("SNAPSHOT_ISOLATION", scope)
        self.expect_kw("READ")
        if self.accept_kw("COMMITTED"):
            return A.IsolationLevelQuery("READ_COMMITTED", scope)
        self.expect_kw("UNCOMMITTED")
        return A.IsolationLevelQuery("READ_UNCOMMITTED", scope)

    def parse_create_trigger(self) -> A.TriggerQuery:
        self.expect_kw("CREATE")
        self.expect_kw("TRIGGER")
        name = self.name_token()
        event = None
        if self.accept_kw("ON"):
            parts = []
            while self.cur.type == T.KEYWORD and self.cur.value in (
                    "CREATE", "UPDATE", "DELETE", "VERTICES", "EDGES"):
                parts.append(self.advance().value)
            event = " ".join(parts) if parts else None
        phase_tok = self.expect_kw("BEFORE", "AFTER")
        self.expect_kw("COMMIT")
        self.expect_kw("EXECUTE")
        # statement: rest of the input until EOF/';'
        start = self.cur.pos
        # capture raw text from token stream positions
        depth = 0
        last = self.cur
        while not self.at(T.EOF) and not (self.at(";") and depth == 0):
            last = self.advance()
        raw_end = last.pos + (len(str(last.value)) if last.value else 1)
        statement = self._source_slice(start)
        return A.TriggerQuery("create", name=name, event=event,
                              phase=phase_tok.value, statement=statement)

    _source: str = ""

    def _source_slice(self, start: int) -> str:
        # Parser doesn't retain source by default; tokenizer pos is enough
        # only if the caller provided it. parse() wires it below.
        return self._source[start:].rstrip("; \n\t") if self._source else ""

    def parse_fg_items(self) -> list:
        if self.accept("*"):
            return ["*"]
        items = []
        self.expect(":")
        items.append(self.name_token())
        while self.accept(","):
            self.expect(":")
            items.append(self.name_token())
        return items

    def parse_auth(self) -> A.AuthQuery:
        first = self.advance()  # CREATE/DROP/SET
        if first.value == "SET":
            self.expect_kw("PASSWORD")
            self.expect_kw("TO")
            pw = self.parse_expression()
            return A.AuthQuery("set_password", password=pw)
        self.expect_kw("USER")
        user = self.name_token()
        if first.value == "DROP":
            return A.AuthQuery("drop_user", user=user)
        pw = None
        # reference grammar: CREATE USER user ( IDENTIFIED BY literal )?
        # (MemgraphCypher.g4:498)
        if self.at(T.IDENT) and self.cur.value.upper() == "IDENTIFIED":
            self.advance()
            self.expect_kw("BY")
            pw = self.parse_expression()
        elif self.accept_kw("PASSWORD"):
            pw = self.parse_expression()
        return A.AuthQuery("create_user", user=user, password=pw)

    # --- Cypher query -------------------------------------------------------

    def parse_cypher_query(self) -> A.CypherQuery:
        commit_frequency = self.parse_periodic_commit()
        first = self.parse_single_query()
        unions = []
        while self.at_kw("UNION"):
            self.advance()
            union_all = bool(self.accept_kw("ALL"))
            unions.append((union_all, self.parse_single_query()))
        mem = None
        if self.at_kw("QUERY"):
            # trailing `QUERY MEMORY LIMIT n MB|KB` / `QUERY MEMORY
            # UNLIMITED` (reference grammar Cypher.g4:134-136)
            self.advance()
            mem = self.parse_memory_limit()
        if commit_frequency is not None and unions:
            self.error("periodic commit is not allowed with UNION")
        return A.CypherQuery(first, unions, memory_limit=mem,
                             commit_frequency=commit_frequency)

    def parse_periodic_commit(self):
        """Leading `USING PERIODIC COMMIT n` pre-query directive
        (reference: MemgraphCypher.g4:405,413). Other USING directives
        (INDEX / HOPS LIMIT / PARALLEL EXECUTION) attach to MATCH and are
        parsed there; only PERIODIC COMMIT legally precedes the first
        clause (`USING PERIODIC COMMIT 500 LOAD CSV ... CREATE ...`)."""
        if not self.at_kw("USING"):
            return None
        self.advance()
        self.expect_kw("PERIODIC")
        self.expect_kw("COMMIT")
        if self.at(T.PARAM):
            freq = A.Parameter(self.advance().value)
        else:
            freq = self.expect(T.INT).value
            if freq < 1:
                self.error("periodic commit frequency must be >= 1")
        return freq

    def parse_tenant_profile(self, action: str) -> "A.TenantProfileQuery":
        """TENANT PROFILE grammar (reference MemgraphCypher.g4:995-1001):
        CREATE TENANT PROFILE p LIMIT k v[, ...] / ALTER ... SET ... /
        DROP TENANT PROFILE p / SET TENANT PROFILE ON DATABASE db TO p /
        CLEAR TENANT PROFILE ON DATABASE db. Caller consumed the leading
        verb; cursor sits at TENANT."""
        self.advance()                  # TENANT
        if not (self.at_kw("PROFILE") or (
                self.at(T.IDENT)
                and self.cur.value.upper() == "PROFILE")):
            self.error("expected PROFILE after TENANT")
        self.advance()
        if action == "assign":
            self.expect_kw("ON")
            self.expect_kw("DATABASE")
            db = self.name_token()
            self.expect_kw("TO")
            return A.TenantProfileQuery("assign", name=self.name_token(),
                                        database=db)
        if action == "clear":
            self.expect_kw("ON")
            self.expect_kw("DATABASE")
            return A.TenantProfileQuery("clear",
                                        database=self.name_token())
        name = self.name_token()
        if action == "drop":
            return A.TenantProfileQuery("drop", name=name)
        if action == "create":
            self.expect_kw("LIMIT")
        else:                           # alter
            self.expect_kw("SET")
        return A.TenantProfileQuery(action, name=name,
                                    limits=self.parse_limit_list())

    def parse_limit_list(self) -> dict:
        """k v pairs: `memory_limit 100MB, ...`; UNLIMITED -> None."""
        limits: dict = {}
        while True:
            key = self.name_token().lower()
            if self.accept_kw("UNLIMITED"):
                limits[key] = None
            else:
                amount = self.expect(T.INT).value
                if self.at(T.IDENT) and self.cur.value.upper() in ("MB",
                                                                   "KB"):
                    unit = self.advance().value.upper()
                    amount *= 1024 * 1024 if unit == "MB" else 1024
                limits[key] = amount
            if not self.accept(","):
                return limits

    def parse_memory_limit(self) -> "Optional[int]":
        self.expect_kw("MEMORY")
        if self.accept_kw("UNLIMITED"):
            return None
        self.expect_kw("LIMIT")
        amount = self.expect(T.INT).value
        if amount < 1:
            self.error("memory limit must be positive")
        unit = self.name_token().upper()
        if unit == "MB":
            return amount * 1024 * 1024
        if unit == "KB":
            return amount * 1024
        self.error("expected MB or KB after the memory limit")

    def parse_single_query(self) -> A.SingleQuery:
        clauses: list[A.Clause] = []
        while True:
            clause = self.try_parse_clause()
            if clause is None:
                break
            clauses.append(clause)
        if not clauses:
            self.error("expected a query clause")
        return A.SingleQuery(clauses)

    def try_parse_clause(self) -> Optional[A.Clause]:
        if self.at_kw("MATCH"):
            return self.parse_match(optional=False)
        if self.at_kw("OPTIONAL"):
            self.advance()
            self.expect_kw("MATCH")
            return self.parse_match(optional=True, consumed=True)
        if self.at_kw("CREATE"):
            self.advance()
            return A.Create(self.parse_pattern_list())
        if self.at_kw("MERGE"):
            return self.parse_merge()
        if self.at_kw("SET"):
            self.advance()
            return A.SetClause(self.parse_set_items())
        if self.at_kw("REMOVE"):
            return self.parse_remove()
        if self.at_kw("DELETE"):
            self.advance()
            return self.parse_delete(detach=False)
        if self.at_kw("DETACH"):
            self.advance()
            self.expect_kw("DELETE")
            return self.parse_delete(detach=True)
        if self.at_kw("RETURN"):
            self.advance()
            return A.Return(self.parse_return_body())
        if self.at_kw("WITH"):
            self.advance()
            body = self.parse_return_body()
            where = None
            if self.accept_kw("WHERE"):
                where = self.parse_expression()
            return A.With(body, where)
        if self.at_kw("UNWIND"):
            self.advance()
            expr = self.parse_expression()
            self.expect_kw("AS")
            var = self.name_token()
            return A.Unwind(expr, var)
        if self.at_kw("CALL"):
            return self.parse_call()
        if self.at_kw("FOREACH"):
            return self.parse_foreach()
        if self.at_kw("LOAD"):
            return self.parse_load()
        return None

    def parse_load(self):
        self.expect_kw("LOAD")
        if self.accept_kw("CSV"):
            self.expect_kw("FROM")
            file_expr = self.parse_expression()
            with_header = False
            if self.accept_kw("WITH"):
                self.expect_kw("HEADER")
                with_header = True
            elif self.accept_kw("NO"):
                self.expect_kw("HEADER")
            ignore_bad = False
            if self.at(T.IDENT) and self.cur.value.upper() == "IGNORE":
                self.advance()
                if self.at(T.IDENT) and self.cur.value.upper() == "BAD":
                    self.advance()
                ignore_bad = True
            delimiter = quote = None
            while True:
                if self.accept_kw("FIELDTERMINATOR"):
                    delimiter = self.parse_expression()
                    continue
                if self.at(T.IDENT) and self.cur.value.upper() == "DELIMITER":
                    self.advance()
                    delimiter = self.parse_expression()
                    continue
                if self.at(T.IDENT) and self.cur.value.upper() == "QUOTE":
                    self.advance()
                    quote = self.parse_expression()
                    continue
                break
            self.expect_kw("AS")
            var = self.name_token()
            return A.LoadCsv(file_expr, var, with_header, ignore_bad,
                             delimiter, quote)
        kind = self.name_token().upper()
        if kind == "JSONL":
            self.expect_kw("FROM")
            file_expr = self.parse_expression()
            self.expect_kw("AS")
            return A.LoadJsonl(file_expr, self.name_token())
        if kind == "PARQUET":
            self.expect_kw("FROM")
            file_expr = self.parse_expression()
            self.expect_kw("AS")
            return A.LoadParquet(file_expr, self.name_token())
        self.error(f"unsupported LOAD source {kind}")

    def parse_match(self, optional: bool, consumed=False) -> A.Match:
        if not consumed:
            self.expect_kw("MATCH")
        patterns = self.parse_pattern_list()
        index_hints = []
        hops_limit = None
        parallel = False
        while self.at_kw("USING"):
            self.advance()
            if self.accept_kw("PARALLEL"):
                self.expect_kw("EXECUTION")
                parallel = True
            elif self.accept_kw("INDEX"):
                var = self.name_token()
                self.expect(":")
                label = self.name_token()
                props = []
                if self.accept("("):
                    props.append(self.name_token())
                    while self.accept(","):
                        props.append(self.name_token())
                    self.expect(")")
                index_hints.append(A.IndexHint(var, label, props))
            elif self.accept_kw("HOPS"):
                self.expect_kw("LIMIT")
                hops_limit = self.expect(T.INT).value
            else:
                self.error("expected INDEX, HOPS LIMIT or PARALLEL "
                           "EXECUTION after USING")
        where = None
        if self.accept_kw("WHERE"):
            where = self.parse_expression()
        return A.Match(patterns, where, optional, index_hints, hops_limit,
                       parallel)

    def parse_merge(self) -> A.Merge:
        self.expect_kw("MERGE")
        pattern = self.parse_pattern()
        on_create, on_match = [], []
        while self.at_kw("ON"):
            self.advance()
            which = self.expect_kw("CREATE", "MATCH").value
            self.expect_kw("SET")
            items = self.parse_set_items()
            (on_create if which == "CREATE" else on_match).extend(items)
        return A.Merge(pattern, on_create, on_match)

    def parse_set_items(self) -> list[A.SetItem]:
        items = [self.parse_set_item()]
        while self.accept(","):
            items.append(self.parse_set_item())
        return items

    def parse_set_item(self) -> A.SetItem:
        target = self.parse_expression(no_top_equals=True)
        if self.accept("="):
            value = self.parse_expression()
            if isinstance(target, A.PropertyLookup):
                return A.SetItem("prop", target, value)
            if isinstance(target, A.Identifier):
                return A.SetItem("var_assign", target, value)
            self.error("invalid SET target")
        if self.accept("+="):
            value = self.parse_expression()
            return A.SetItem("var_update", target, value)
        if isinstance(target, A.LabelsTest):
            return A.SetItem("label", target.expr, target.labels)
        self.error("invalid SET item")

    def parse_remove(self) -> A.Remove:
        self.expect_kw("REMOVE")
        items = [self.parse_remove_item()]
        while self.accept(","):
            items.append(self.parse_remove_item())
        return A.Remove(items)

    def parse_remove_item(self) -> A.RemoveItem:
        expr = self.parse_expression(no_top_equals=True)
        if isinstance(expr, A.PropertyLookup):
            return A.RemoveItem("prop", expr)
        if isinstance(expr, A.LabelsTest):
            return A.RemoveItem("label", expr.expr, expr.labels)
        self.error("invalid REMOVE item")

    def parse_delete(self, detach: bool) -> A.Delete:
        exprs = [self.parse_expression()]
        while self.accept(","):
            exprs.append(self.parse_expression())
        return A.Delete(exprs, detach)

    def parse_return_body(self) -> A.ReturnBody:
        distinct = bool(self.accept_kw("DISTINCT"))
        star = False
        items: list[tuple[A.Expr, Optional[str]]] = []
        if self.accept("*"):
            star = True
            while self.accept(","):
                items.append(self.parse_return_item())
        else:
            items.append(self.parse_return_item())
            while self.accept(","):
                items.append(self.parse_return_item())
        order_by: list[A.SortItem] = []
        if self.accept_kw("ORDER"):
            self.expect_kw("BY")
            order_by.append(self.parse_sort_item())
            while self.accept(","):
                order_by.append(self.parse_sort_item())
        skip = limit = None
        if self.accept_kw("SKIP"):
            skip = self.parse_expression()
        if self.accept_kw("LIMIT"):
            limit = self.parse_expression()
        return A.ReturnBody(distinct, items, star, order_by, skip, limit)

    def parse_return_item(self):
        start = self.cur.pos
        expr = self.parse_expression()
        end = self.cur.pos  # first token NOT part of the expression
        if self.accept_kw("AS"):
            return (expr, self.name_token(), None)
        # unaliased item: the column name is the VERBATIM source text of
        # the expression, case and spacing included (openCypher TCK
        # ColumnNameAcceptance "Keeping used expression")
        verbatim = (self._source[start:end].strip()
                    if self._source is not None else None)
        return (expr, None, verbatim)

    def parse_sort_item(self) -> A.SortItem:
        expr = self.parse_expression()
        asc = True
        if self.accept_kw("ASC", "ASCENDING"):
            asc = True
        elif self.accept_kw("DESC", "DESCENDING"):
            asc = False
        return A.SortItem(expr, asc)

    def parse_call(self):
        self.expect_kw("CALL")
        if self.at("{"):
            self.advance()
            sub = self.parse_single_query()
            self.expect("}")
            batch_rows = None
            if self.accept_kw("IN"):
                self.expect_kw("TRANSACTIONS")
                self.expect_kw("OF")  # reference grammar: OF n ROWS required
                batch_rows = self.expect(T.INT).value
                if batch_rows < 1:
                    self.error("IN TRANSACTIONS batch size must be >= 1")
                if not (self.at(T.IDENT)
                        and self.cur.value.upper() == "ROWS") \
                        and not self.at_kw("ROW"):
                    self.error("expected ROWS after the batch size")
                self.advance()
            return A.CallSubquery(sub, batch_rows)
        parts = [self.name_token()]
        while self.accept("."):
            parts.append(self.name_token())
        name = ".".join(parts)
        # args=None (no parens) is distinct from args=[] (empty parens):
        # standalone CALL without parens takes arguments implicitly from
        # query parameters; in-query CALL requires explicit parens
        # (TCK ProcedureCallAcceptance: InvalidArgumentPassingMode)
        args: Optional[list[A.Expr]] = None
        if self.accept("("):
            args = []
            if not self.at(")"):
                args.append(self.parse_expression())
                while self.accept(","):
                    args.append(self.parse_expression())
            self.expect(")")
        mem_limit = None
        if self.at_kw("PROCEDURE"):
            # CALL proc() PROCEDURE MEMORY LIMIT n MB|KB (Cypher.g4:138)
            self.advance()
            mem_limit = self.parse_memory_limit()
        yields: list[tuple[str, Optional[str]]] = []
        yield_star = False
        yield_dash = False
        where = None
        if self.accept_kw("YIELD"):
            if self.accept("*"):
                yield_star = True
            elif self.accept("-"):
                yield_dash = True  # explicitly yield nothing
            else:
                yields.append(self.parse_yield_item())
                while self.accept(","):
                    yields.append(self.parse_yield_item())
            if self.accept_kw("WHERE"):
                where = self.parse_expression()
        return A.CallProcedure(name, args, yields, yield_star, where,
                               yield_dash)

    def parse_yield_item(self):
        field = self.name_token()
        alias = None
        if self.accept_kw("AS"):
            alias = self.name_token()
        return (field, alias)

    def parse_foreach(self) -> A.Foreach:
        self.expect_kw("FOREACH")
        self.expect("(")
        var = self.name_token()
        self.expect_kw("IN")
        expr = self.parse_expression()
        self.expect("|")
        updates: list[A.Clause] = []
        while not self.at(")"):
            clause = self.try_parse_clause()
            if clause is None:
                self.error("expected an update clause in FOREACH")
            updates.append(clause)
        self.expect(")")
        return A.Foreach(var, expr, updates)

    # --- patterns -----------------------------------------------------------

    def parse_pattern_list(self) -> list[A.Pattern]:
        patterns = [self.parse_pattern()]
        while self.accept(","):
            patterns.append(self.parse_pattern())
        return patterns

    def parse_pattern(self) -> A.Pattern:
        variable = None
        if self.at(T.IDENT) and self.peek().type == "=":
            variable = self.advance().value
            self.advance()  # '='
        elements = [self.parse_node_pattern()]
        while self.at("-") or self.at("<-") or self.at("--") or self.at("<"):
            edge = self.parse_edge_pattern()
            node = self.parse_node_pattern()
            elements.append(edge)
            elements.append(node)
        return A.Pattern(variable, elements)

    def parse_node_pattern(self) -> A.NodePattern:
        self.expect("(")
        variable = None
        labels: list[str] = []
        props = None
        if self.at(T.IDENT) or (self.cur.type == T.KEYWORD
                                and not self.at(")")
                                and self.peek().type in (":", ")", "{")):
            variable = self.name_token()
        while self.accept(":"):
            labels.append(self.name_token())
        if self.at("{") or self.at(T.PARAM):
            props = self.parse_map_or_param()
        self.expect(")")
        return A.NodePattern(variable, labels, props)

    def parse_edge_pattern(self) -> A.EdgePattern:
        # arrows: -[..]-> | <-[..]- | -[..]- | --> | <-- | --
        direction = "both"
        if self.accept("<-"):
            direction = "in"
            left_consumed = True
        elif self.accept("<"):
            self.expect("-")
            direction = "in"
        elif self.accept("--"):
            # bare '--' or '-->' handled below
            if self.accept(">"):
                return A.EdgePattern(None, [], "out")
            return A.EdgePattern(None, [], "both")
        else:
            self.expect("-")

        variable = None
        types: list[str] = []
        props = None
        var_length = False
        min_hops = max_hops = None
        algo = None
        weight_lambda = None
        filter_lambda = None
        total_weight = None
        if self.accept("["):
            if self.at(T.IDENT) and self.peek().type in (":", "]", "*", "{"):
                variable = self.advance().value
            if self.accept(":"):
                types.append(self.name_token())
                while self.accept("|"):
                    self.accept(":")
                    types.append(self.name_token())
            if self.accept("*"):
                var_length = True
                from .lexer import T as TT
                if self.at(TT.IDENT) and self.cur.value.upper() in (
                        "BFS", "WSHORTEST", "ALLSHORTEST", "KSHORTEST"):
                    algo = self.advance().value.lower()
                if self.at(TT.INT):
                    min_hops = A.Literal(self.advance().value)
                    if self.accept(".."):
                        if self.at(TT.INT):
                            max_hops = A.Literal(self.advance().value)
                    else:
                        max_hops = min_hops
                elif self.accept(".."):
                    if self.at(TT.INT):
                        max_hops = A.Literal(self.advance().value)
                elif self.at(T.FLOAT):
                    # "*1.5" is invalid; but "*1..2" lexes as INT '..' INT
                    self.error("invalid variable-length bounds")
                # lambdas: weight first for WSHORTEST/ALLSHORTEST, then an
                # optional filter lambda (reference: MemgraphCypher grammar)
                if algo in ("wshortest", "allshortest", "kshortest") \
                        and self.at("("):
                    weight_lambda = self._parse_lambda()
                    if self.at(T.IDENT) and self.peek().type in ("]", "("):
                        total_weight = self.advance().value
                if self.at("("):
                    filter_lambda = self._parse_lambda()
            if self.at("{") or self.at(T.PARAM):
                props = self.parse_map_or_param()
            self.expect("]")
        # closing arrow
        if direction == "in":
            if self.accept("->"):   # bare '<-->' lexes as '<-' + '->'
                direction = "both"
            else:
                self.expect("-")
                if self.accept(">"):
                    direction = "both"  # <-[..]-> treated as undirected
        else:
            if self.accept("->"):
                direction = "out"
            elif self.accept("-"):
                if self.accept(">"):
                    direction = "out"
                else:
                    direction = "both"
            elif self.accept(">"):
                direction = "out"
            else:
                self.error("malformed relationship pattern")
        return A.EdgePattern(variable, types, direction, props, var_length,
                             min_hops, max_hops, algo, weight_lambda,
                             filter_lambda, total_weight)

    def _parse_lambda(self) -> A.Lambda:
        self.expect("(")
        edge_var = self.name_token()
        self.expect(",")
        node_var = self.name_token()
        self.expect("|")
        expr = self.parse_expression()
        self.expect(")")
        return A.Lambda(edge_var, node_var, expr)

    def parse_map_or_param(self):
        if self.at(T.PARAM):
            return A.Parameter(self.advance().value)
        self.expect("{")
        out: dict[str, A.Expr] = {}
        if not self.at("}"):
            while True:
                key = self.name_token() if not self.at(T.STRING) else self.advance().value
                self.expect(":")
                out[key] = self.parse_expression()
                if not self.accept(","):
                    break
        self.expect("}")
        return out

    # --- expressions (precedence climbing) ---------------------------------

    def parse_expression(self, no_top_equals: bool = False) -> A.Expr:
        if no_top_equals:
            return self._parse_or_stop_equals()
        return self.parse_or()

    def _parse_or_stop_equals(self) -> A.Expr:
        # For SET items: parse a primary+postfix chain only (target position)
        return self.parse_postfix(self.parse_primary())

    def parse_or(self) -> A.Expr:
        left = self.parse_xor()
        while self.at_kw("OR"):
            self.advance()
            left = A.Binary("OR", left, self.parse_xor())
        return left

    def parse_xor(self) -> A.Expr:
        left = self.parse_and()
        while self.at_kw("XOR"):
            self.advance()
            left = A.Binary("XOR", left, self.parse_and())
        return left

    def parse_and(self) -> A.Expr:
        left = self.parse_not()
        while self.at_kw("AND"):
            self.advance()
            left = A.Binary("AND", left, self.parse_not())
        return left

    def parse_not(self) -> A.Expr:
        if self.accept_kw("NOT"):
            return A.Unary("NOT", self.parse_not())
        return self.parse_comparison()

    _CMP = ("=", "<>", "<", ">", "<=", ">=")

    def parse_comparison(self) -> A.Expr:
        left = self.parse_additive()
        # chained comparisons: a < b < c → (a<b) AND (b<c)
        comparisons = []
        while self.cur.type in self._CMP:
            op = self.advance().type
            right = self.parse_additive()
            comparisons.append((op, right))
        if not comparisons:
            return self._parse_special_predicates(left)
        result = None
        prev = left
        for op, right in comparisons:
            cmp_node = A.Binary(op, prev, right)
            result = cmp_node if result is None else A.Binary("AND", result,
                                                              cmp_node)
            prev = right
        return result

    def _parse_special_predicates(self, left: A.Expr) -> A.Expr:
        while True:
            if self.at_kw("IS"):
                save = self.i
                self.advance()
                if self.accept_kw("NULL"):
                    left = A.IsNull(left, negated=False)
                    continue
                if self.accept_kw("NOT"):
                    if self.accept_kw("NULL"):
                        left = A.IsNull(left, negated=True)
                        continue
                self.i = save
                break
            if self.at_kw("IN"):
                self.advance()
                left = A.Binary("IN", left, self.parse_additive())
                continue
            if self.at_kw("STARTS"):
                self.advance()
                self.expect_kw("WITH")
                left = A.Binary("STARTS WITH", left, self.parse_additive())
                continue
            if self.at_kw("ENDS"):
                self.advance()
                self.expect_kw("WITH")
                left = A.Binary("ENDS WITH", left, self.parse_additive())
                continue
            if self.at_kw("CONTAINS"):
                self.advance()
                left = A.Binary("CONTAINS", left, self.parse_additive())
                continue
            if self.at("=~"):
                self.advance()
                left = A.Binary("=~", left, self.parse_additive())
                continue
            break
        return left

    def parse_additive(self) -> A.Expr:
        left = self.parse_multiplicative()
        while self.at("+") or self.at("-"):
            op = self.advance().type
            left = A.Binary(op, left, self.parse_multiplicative())
        return left

    def parse_multiplicative(self) -> A.Expr:
        left = self.parse_power()
        while self.at("*") or self.at("/") or self.at("%"):
            op = self.advance().type
            left = A.Binary(op, left, self.parse_power())
        return left

    def parse_power(self) -> A.Expr:
        left = self.parse_unary()
        if self.at("^"):
            self.advance()
            return A.Binary("^", left, self.parse_power())  # right-assoc
        return left

    def parse_unary(self) -> A.Expr:
        if self.at("-"):
            self.advance()
            return A.Unary("-", self.parse_unary())
        if self.at("+"):
            self.advance()
            return A.Unary("+", self.parse_unary())
        return self.parse_postfix(self.parse_primary())

    def parse_postfix(self, expr: A.Expr) -> A.Expr:
        while True:
            if self.at("."):
                self.advance()
                expr = A.PropertyLookup(expr, self.name_token())
                continue
            if self.at("["):
                self.advance()
                if self.accept(".."):
                    hi = None if self.at("]") else self.parse_expression()
                    self.expect("]")
                    expr = A.Slice(expr, None, hi)
                    continue
                index = None if self.at("..") else self.parse_expression()
                if self.accept(".."):
                    hi = None if self.at("]") else self.parse_expression()
                    self.expect("]")
                    expr = A.Slice(expr, index, hi)
                    continue
                self.expect("]")
                expr = A.Subscript(expr, index)
                continue
            if self.at(":") and isinstance(expr, (A.Identifier,
                                                  A.PropertyLookup,
                                                  A.FunctionCall,
                                                  A.LabelsTest)):
                # labels test: n:Person:Employee
                labels = []
                while self.accept(":"):
                    labels.append(self.name_token())
                if isinstance(expr, A.LabelsTest):
                    expr.labels.extend(labels)
                else:
                    expr = A.LabelsTest(expr, labels)
                continue
            break
        return expr

    def parse_primary(self) -> A.Expr:
        tok = self.cur
        if tok.type == T.INT or tok.type == T.FLOAT or tok.type == T.STRING:
            self.advance()
            return A.Literal(tok.value)
        if tok.type == T.PARAM:
            self.advance()
            return A.Parameter(tok.value)
        if tok.is_kw("TRUE"):
            self.advance()
            return A.Literal(True)
        if tok.is_kw("FALSE"):
            self.advance()
            return A.Literal(False)
        if tok.is_kw("NULL"):
            self.advance()
            return A.Literal(None)
        if tok.is_kw("COUNT") and self.peek().type == "(" \
                and self.peek(2).type == "*":
            self.advance(); self.advance(); self.advance()
            self.expect(")")
            return A.CountStar()
        if tok.is_kw("CASE"):
            return self.parse_case()
        if tok.is_kw("EXISTS") and self.peek().type == "(":
            self.advance()
            self.expect("(")
            if self.at("("):
                pattern = self.parse_pattern()
                self.expect(")")
                return A.PatternExpr(pattern)
            expr = self.parse_expression()
            self.expect(")")
            if not isinstance(expr, (A.PropertyLookup, A.Identifier,
                                     A.Subscript, A.PatternExpr)):
                # TCK SemanticErrorAcceptance: InvalidArgumentExpression
                raise SyntaxException(
                    "InvalidArgumentExpression: exists() expects a "
                    "property access or a pattern")
            return A.IsNull(expr, negated=True)
        if tok.is_kw("ALL", "ANY", "NONE", "SINGLE") and self.peek().type == "(":
            kind = self.advance().value
            self.expect("(")
            var = self.name_token()
            self.expect_kw("IN")
            lst = self.parse_expression()
            self.expect_kw("WHERE")
            where = self.parse_expression()
            self.expect(")")
            return A.Quantifier(kind, var, lst, where)
        if (tok.type == T.IDENT and tok.value.lower() == "reduce"
                and self.peek().type == "("):
            self.advance()
            self.expect("(")
            acc = self.name_token()
            self.expect("=")
            init = self.parse_expression()
            self.expect(",")
            var = self.name_token()
            self.expect_kw("IN")
            lst = self.parse_expression()
            self.expect("|")
            expr = self.parse_expression()
            self.expect(")")
            return A.Reduce(acc, init, var, lst, expr)
        if tok.is_kw("COALESCE") and self.peek().type == "(":
            self.advance()
            return self._finish_function_call("coalesce")
        if tok.type == "(":
            # sub-expression OR a pattern expression like (n)-[:X]->(m)
            save = self.i
            try:
                pattern = self.parse_pattern()
                if (len(pattern.elements) > 1
                        and (self.at(T.EOF) or not self.at("("))):
                    return A.PatternExpr(pattern, exists_form=False)
                raise SyntaxException("not a pattern")
            except SyntaxException:
                self.i = save
            self.advance()
            expr = self.parse_expression()
            self.expect(")")
            return expr
        if tok.type == "[":
            return self.parse_list_or_comprehension()
        if tok.type == "{":
            items = self.parse_map_or_param()
            return A.MapLiteral(items)
        if tok.type == T.IDENT or tok.type == T.KEYWORD:
            if self.peek().type == "::":
                enum_name = self.name_token()
                self.advance()  # '::'
                return A.EnumLiteral(enum_name, self.name_token())
            # function call or identifier (possibly namespaced)
            if self.peek().type == "(" or (self.peek().type == "."
                                           and self._looks_like_ns_call()):
                return self.parse_function_or_ident()
            name = self.name_token()
            return A.Identifier(name)
        self.error(f"unexpected token {self._desc(tok)} in expression")

    def _looks_like_ns_call(self) -> bool:
        """ident '.' ident ... '(' — namespaced function call."""
        k = self.i
        toks = self.toks
        if toks[k].type not in (T.IDENT, T.KEYWORD):
            return False
        k += 1
        saw_dot = False
        while (k + 1 < len(toks) and toks[k].type == "."
               and toks[k + 1].type in (T.IDENT, T.KEYWORD)):
            saw_dot = True
            k += 2
        return saw_dot and k < len(toks) and toks[k].type == "("

    def parse_function_or_ident(self) -> A.Expr:
        parts = [self.name_token()]
        while self.at(".") and self.peek().type in (T.IDENT, T.KEYWORD):
            # only consume dots that lead to '(' eventually
            if not self._dots_lead_to_call():
                break
            self.advance()
            parts.append(self.name_token())
        name = ".".join(parts)
        if self.at("("):
            return self._finish_function_call(name.lower())
        if len(parts) == 1:
            return A.Identifier(parts[0])
        # ident.prop fallback
        expr: A.Expr = A.Identifier(parts[0])
        for p in parts[1:]:
            expr = A.PropertyLookup(expr, p)
        return expr

    def _dots_lead_to_call(self) -> bool:
        k = self.i
        toks = self.toks
        while (k + 1 < len(toks) and toks[k].type == "."
               and toks[k + 1].type in (T.IDENT, T.KEYWORD)):
            k += 2
        return k < len(toks) and toks[k].type == "("

    def _finish_function_call(self, name: str) -> A.FunctionCall:
        self.expect("(")
        distinct = bool(self.accept_kw("DISTINCT"))
        args: list[A.Expr] = []
        if not self.at(")"):
            if self.accept("*"):
                self.expect(")")
                if name == "count":
                    return A.CountStar()
                self.error(f"'*' argument not supported for {name}()")
            args.append(self.parse_expression())
            while self.accept(","):
                args.append(self.parse_expression())
        self.expect(")")
        return A.FunctionCall(name, args, distinct)

    def parse_case(self) -> A.CaseExpr:
        self.expect_kw("CASE")
        test = None
        if not self.at_kw("WHEN"):
            test = self.parse_expression()
        whens: list[tuple[A.Expr, A.Expr]] = []
        while self.accept_kw("WHEN"):
            cond = self.parse_expression()
            self.expect_kw("THEN")
            whens.append((cond, self.parse_expression()))
        default = None
        if self.accept_kw("ELSE"):
            default = self.parse_expression()
        self.expect_kw("END")
        if not whens:
            self.error("CASE requires at least one WHEN")
        return A.CaseExpr(test, whens, default)

    def parse_list_or_comprehension(self) -> A.Expr:
        self.expect("[")
        if self.at("]"):
            self.advance()
            return A.ListLiteral([])
        # pattern comprehension: [(n)-[]->(m) ... | expr], optionally with
        # a named path [p = (n)-->() | p] (reference grammar
        # Cypher.g4:334 patternComprehension)
        if self.at("(") or (self.at(T.IDENT) and self.peek().type == "="):
            save = self.i
            try:
                pattern = self.parse_pattern()
                if len(pattern.elements) > 1 and (self.at("|")
                                                  or self.at_kw("WHERE")):
                    where = None
                    if self.accept_kw("WHERE"):
                        where = self.parse_expression()
                    self.expect("|")
                    proj = self.parse_expression()
                    self.expect("]")
                    return A.PatternComprehension(pattern, where, proj)
                raise SyntaxException("not a pattern comprehension")
            except SyntaxException:
                self.i = save
        # lookahead: name IN → comprehension (the variable may lex as a
        # KEYWORD, e.g. `[key IN keys(r) | ...]` — KEY is a keyword)
        if (self.cur.type in (T.IDENT, T.KEYWORD)
                and self.peek().is_kw("IN")):
            var = self.name_token()
            self.advance()  # IN
            lst = self.parse_expression()
            where = None
            proj = None
            if self.accept_kw("WHERE"):
                where = self.parse_expression()
            if self.accept("|"):
                proj = self.parse_expression()
            self.expect("]")
            return A.ListComprehension(var, lst, where, proj)
        items = [self.parse_expression()]
        while self.accept(","):
            items.append(self.parse_expression())
        self.expect("]")
        return A.ListLiteral(items)


def parse_with_source(text: str):
    """parse() variant that retains source for trigger statements."""
    p = Parser(tokenize(text))
    p._source = text
    return p.parse_statement()
