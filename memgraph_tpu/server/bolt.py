"""Bolt protocol server (asyncio).

Counterpart of the reference's Bolt stack
(/root/reference/src/communication/bolt/ — session state machine at
bolt/v1/session.hpp:55, message handlers at bolt/v1/states/executing.hpp):
handshake (versions 4.3/4.4/5.x), chunked message framing, HELLO/LOGON
auth, RUN/PULL/DISCARD with qid-less streaming, BEGIN/COMMIT/ROLLBACK,
RESET/GOODBYE, value conversion between the engine's Python values and
PackStream structures (the glue/communication.cpp analog).
"""

from __future__ import annotations

import asyncio
import logging
import os
import struct

from ..exceptions import MemgraphTpuError, QueryException
from ..observability import trace as mgtrace
from ..query.interpreter import Interpreter, InterpreterContext
from ..query.values import Path
from ..storage.storage import EdgeAccessor, VertexAccessor
from ..utils.point import Point
from ..utils.temporal import (Date, Duration, LocalDateTime, LocalTime,
                              ZonedDateTime)
from . import packstream as ps

log = logging.getLogger(__name__)

BOLT_MAGIC = b"\x60\x60\xB0\x17"
# value_to_bolt emits version-appropriate structures: v5 (element ids, UTC
# datetimes) for 5.x sessions, legacy 3-field/5-field structures for 4.x
SUPPORTED_VERSIONS = [(5, 2), (5, 1), (5, 0), (4, 4), (4, 3)]
LEGACY_DATETIME = 0x46  # 4.x offset datetime ('F')
LEGACY_DATETIME_ZONE_ID = 0x66  # 4.x zoned datetime ('f')

# message signatures
M_HELLO = 0x01
M_LOGON = 0x6A
M_LOGOFF = 0x6B
M_GOODBYE = 0x02
M_RESET = 0x0F
M_RUN = 0x10
M_BEGIN = 0x11
M_COMMIT = 0x12
M_ROLLBACK = 0x13
M_DISCARD = 0x2F
M_PULL = 0x3F
M_ROUTE = 0x66
M_SUCCESS = 0x70
M_RECORD = 0x71
M_IGNORED = 0x7E
M_FAILURE = 0x7F


def value_to_bolt(v, storage, view, version=(5, 2)):
    """Engine value → PackStream value (glue/communication.cpp analog).
    Structure field sets follow the negotiated protocol version."""
    v5 = version >= (5, 0)
    if v is None or isinstance(v, (bool, int, float, str, bytes)):
        return v
    if isinstance(v, (list, tuple)):
        return [value_to_bolt(x, storage, view, version) for x in v]
    if isinstance(v, dict):
        return {k: value_to_bolt(x, storage, view, version)
                for k, x in v.items()}
    if isinstance(v, VertexAccessor):
        labels = [storage.label_mapper.id_to_name(l) for l in v.labels(view)]
        props = {storage.property_mapper.id_to_name(k):
                 value_to_bolt(val, storage, view, version)
                 for k, val in v.properties(view).items()}
        fields = [v.gid, labels, props]
        if v5:
            fields.append(str(v.gid))  # element_id
        return ps.Structure(ps.S_NODE, fields)
    if isinstance(v, EdgeAccessor):
        props = {storage.property_mapper.id_to_name(k):
                 value_to_bolt(val, storage, view, version)
                 for k, val in v.properties(view).items()}
        fields = [v.gid, v.from_vertex().gid, v.to_vertex().gid,
                  storage.edge_type_mapper.id_to_name(v.edge_type), props]
        if v5:
            fields += [str(v.gid), str(v.from_vertex().gid),
                       str(v.to_vertex().gid)]
        return ps.Structure(ps.S_RELATIONSHIP, fields)
    if isinstance(v, Path):
        nodes = [value_to_bolt(n, storage, view, version)
                 for n in v.vertices()]
        edges = v.edges()
        rels = []
        for e in edges:
            props = {storage.property_mapper.id_to_name(k):
                     value_to_bolt(val, storage, view, version)
                     for k, val in e.properties(view).items()}
            fields = [e.gid,
                      storage.edge_type_mapper.id_to_name(e.edge_type),
                      props]
            if v5:
                fields.append(str(e.gid))
            rels.append(ps.Structure(ps.S_UNBOUND_RELATIONSHIP, fields))
        # index sequence: alternating rel index (1-based) and node index
        seq = []
        node_ids = [n.gid for n in v.vertices()]
        for i, e in enumerate(edges):
            rel_idx = i + 1
            if e.from_vertex().gid == node_ids[i]:
                seq.append(rel_idx)
            else:
                seq.append(-rel_idx)
            seq.append(i + 1)
        return ps.Structure(ps.S_PATH, [nodes, rels, seq])
    if isinstance(v, Date):
        return ps.Structure(ps.S_DATE, [v.d.toordinal() - 719163])  # epoch day
    if isinstance(v, LocalTime):
        return ps.Structure(ps.S_LOCAL_TIME, [v._micros() * 1000])
    if isinstance(v, LocalDateTime):
        micros = v.timestamp_micros()
        return ps.Structure(ps.S_LOCAL_DATETIME,
                            [micros // 1_000_000,
                             (micros % 1_000_000) * 1000])
    if isinstance(v, ZonedDateTime):
        micros = v.timestamp_micros()
        offset = int(v.dt.utcoffset().total_seconds()) if v.dt.utcoffset() \
            else 0
        if not v5:
            # legacy 4.x: wall-clock seconds (local) + offset, tag 'F'
            local = micros + offset * 1_000_000
            return ps.Structure(LEGACY_DATETIME,
                                [local // 1_000_000,
                                 (local % 1_000_000) * 1000, offset])
        return ps.Structure(ps.S_DATETIME,
                            [micros // 1_000_000,
                             (micros % 1_000_000) * 1000, offset])
    if isinstance(v, Duration):
        days, rem = divmod(v.micros, 86_400_000_000)
        seconds, micros = divmod(rem, 1_000_000)
        return ps.Structure(ps.S_DURATION,
                            [0, days, seconds, micros * 1000])
    if isinstance(v, Point):
        if v.crs.dims == 2:
            return ps.Structure(ps.S_POINT_2D, [v.crs.value, v.x, v.y])
        return ps.Structure(ps.S_POINT_3D, [v.crs.value, v.x, v.y, v.z])
    from ..storage.enums import EnumValue
    if isinstance(v, EnumValue):
        return str(v)  # "Name::Value" (reference sends enums as strings)
    raise ps.PackStreamError(f"cannot convert {type(v)!r} to bolt")


def bolt_to_value(v):
    """PackStream input (parameters) → engine value."""
    if isinstance(v, list):
        return [bolt_to_value(x) for x in v]
    if isinstance(v, dict):
        return {k: bolt_to_value(x) for k, x in v.items()}
    if isinstance(v, ps.Structure):
        import datetime as dt
        if v.tag == ps.S_DATE:
            return Date(dt.date.fromordinal(v.fields[0] + 719163))
        if v.tag == ps.S_LOCAL_TIME:
            from ..utils.temporal import _micros_to_time
            return LocalTime(_micros_to_time(v.fields[0] // 1000))
        if v.tag == ps.S_LOCAL_DATETIME:
            sec, nanos = v.fields
            return LocalDateTime(dt.datetime(1970, 1, 1)
                                 + dt.timedelta(seconds=sec,
                                                microseconds=nanos // 1000))
        if v.tag == ps.S_DURATION:
            months, days, seconds, nanos = v.fields
            return Duration.from_parts(days=months * 30 + days,
                                       seconds=seconds,
                                       microseconds=nanos // 1000)
        if v.tag == ps.S_DATETIME:
            sec, nanos, offset = v.fields
            tz = dt.timezone(dt.timedelta(seconds=offset))
            return ZonedDateTime(dt.datetime.fromtimestamp(
                sec + nanos / 1e9, tz))
        if v.tag == ps.S_DATETIME_ZONE_ID:
            sec, nanos, zone = v.fields
            base = dt.datetime.fromtimestamp(sec + nanos / 1e9,
                                             dt.timezone.utc)
            try:
                from zoneinfo import ZoneInfo
                base = base.astimezone(ZoneInfo(zone))
            except (ImportError, KeyError, ValueError, OSError):
                pass  # unknown/unavailable tz db: keep UTC instant
            return ZonedDateTime(base)
        if v.tag == LEGACY_DATETIME:
            # 4.x: local wall-clock seconds + offset
            sec, nanos, offset = v.fields
            tz = dt.timezone(dt.timedelta(seconds=offset))
            utc_micros = sec * 1_000_000 + nanos // 1000 \
                - offset * 1_000_000
            return ZonedDateTime(dt.datetime.fromtimestamp(
                utc_micros / 1e6, tz))
        if v.tag == ps.S_TIME:
            nanos, offset = v.fields
            from ..utils.temporal import _micros_to_time
            # offset-carrying time flattens to LocalTime (engine has no
            # zoned-time type; matches reference behavior for TIME values)
            return LocalTime(_micros_to_time(nanos // 1000))
        if v.tag in (ps.S_POINT_2D, ps.S_POINT_3D):
            from ..utils.point import CrsType
            crs = CrsType(v.fields[0])
            z = v.fields[3] if v.tag == ps.S_POINT_3D else None
            return Point(v.fields[1], v.fields[2], z, crs)
        raise ps.PackStreamError(
            f"unsupported parameter structure 0x{v.tag:02X}")
    return v


class BoltSession:
    """One connection: handshake → auth → message loop.

    The reference's SessionHL analog (glue/SessionHL.hpp): bridges the wire
    protocol to an Interpreter.
    """

    def __init__(self, reader, writer, interpreter_context, auth=None,
                 executor=None):
        self.reader = reader
        self.writer = writer
        self.ictx = interpreter_context
        self.auth = auth
        self.interpreter = Interpreter(interpreter_context)
        self.version: tuple[int, int] = (0, 0)
        self.authenticated = False
        self.failed = False  # FAILURE → ignore until RESET
        self._prepared = None
        import uuid as _uuid
        self.session_id = str(_uuid.uuid4())
        # mgtrace: the session-level root of the current RUN..PULL*
        # exchange (None unless tracing is armed)
        self._bolt_trace = None
        # interpreter work (parse/plan/execute/pull) runs on this pool so
        # one session's long query never blocks the event loop — the
        # reference runs sessions on a work-stealing priority pool
        # (utils/priority_thread_pool.hpp); numpy/JAX sections release
        # the GIL, so columnar scans and device kernels overlap for real.
        # Protocol reads/writes stay on the loop (transports are not
        # thread-safe); per-session ordering is preserved because the
        # message loop awaits each dispatch before reading the next.
        self._executor = executor

    def _register_session(self) -> bool:
        """SHOW ACTIVE USERS INFO registry (reference: GetActiveUsersInfo,
        interpreter.cpp SystemInfoQuery ACTIVE_USERS). Also the
        enforcement point for the user profile `sessions` limit
        (reference: user_profiles.cpp kSessions) — False = refused."""
        import datetime
        sessions = getattr(self.ictx, "active_sessions", None)
        if sessions is None:
            sessions = self.ictx.active_sessions = {}
        username = self.interpreter.username or ""
        profiles = getattr(self.ictx, "user_profiles", None)
        if profiles is not None and username:
            cap = profiles.limit_for_user(username, "sessions")
            if cap is not None:
                live = sum(1 for sid, (u, _t) in sessions.items()
                           if u == username and sid != self.session_id)
                if live >= cap:
                    return False
        ts = datetime.datetime.now(datetime.timezone.utc).isoformat()
        sessions[self.session_id] = (username, ts)
        return True

    def _register_or_refuse(self) -> bool:
        """Register, or send the session-limit refusal; False = refused
        (the failure is already on the wire, caller just returns)."""
        if self._register_session():
            return True
        self.authenticated = False
        self.send_failure(
            "Memgraph.ClientError.Security.Unauthenticated",
            "session limit exceeded for this user's profile")
        return False

    def _unregister_session(self) -> None:
        getattr(self.ictx, "active_sessions", {}).pop(self.session_id, None)

    async def _offload(self, fn, *args):
        if self._executor is None:
            return fn(*args)
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(self._executor, fn, *args)

    # --- wire framing -------------------------------------------------------

    async def _read_exact(self, n: int) -> bytes:
        return await self.reader.readexactly(n)

    async def read_message(self) -> bytes:
        chunks = []
        while True:
            header = await self._read_exact(2)
            size = struct.unpack(">H", header)[0]
            if size == 0:
                if chunks:
                    return b"".join(chunks)
                continue  # noop chunk (keep-alive)
            chunks.append(await self._read_exact(size))

    def write_message(self, data: bytes) -> None:
        pos = 0
        while pos < len(data):
            chunk = data[pos:pos + 0xFFFF]
            self.writer.write(struct.pack(">H", len(chunk)) + chunk)
            pos += len(chunk)
        self.writer.write(b"\x00\x00")

    def send(self, signature: int, *fields) -> None:
        self.write_message(ps.pack(ps.Structure(signature, list(fields))))

    def send_success(self, metadata=None) -> None:
        self.send(M_SUCCESS, metadata or {})

    def send_failure(self, code: str, message: str) -> None:
        self.failed = True
        self._finish_bolt_trace("error")
        self.send(M_FAILURE, {"code": code, "message": message})

    # --- lifecycle ----------------------------------------------------------

    async def run(self) -> None:
        try:
            if not await self.handshake():
                return
            peer = self.writer.get_extra_info("peername")
            log.info("Accepted a connection from %s:%s",
                     *(peer[:2] if peer else ("?", "?")))
            while True:
                data = await self.read_message()
                msg = ps.unpack(data)
                if not isinstance(msg, ps.Structure):
                    raise MemgraphTpuError("malformed bolt message")
                if not await self.dispatch(msg):
                    break
                await self.drain()
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass
        except Exception:
            log.exception("bolt session crashed")
        finally:
            self._finish_bolt_trace("abandoned")
            self._unregister_session()
            self.interpreter.abort()
            self.writer.close()

    async def drain(self):
        await self.writer.drain()

    async def handshake(self) -> bool:
        magic = await self._read_exact(4)
        if magic != BOLT_MAGIC:
            return False
        proposals = await self._read_exact(16)
        chosen = (0, 0)
        for i in range(4):
            major = proposals[i * 4 + 3]
            minor = proposals[i * 4 + 2]
            rng = proposals[i * 4 + 1]
            # a proposal (major, minor, range) offers minors
            # [minor - range, minor]; pick the highest we support
            for (maj, min_) in SUPPORTED_VERSIONS:
                if maj == major and minor >= min_ >= minor - rng:
                    chosen = (maj, min_)
                    break
            if chosen != (0, 0):
                break
        self.writer.write(bytes([0, 0, chosen[1], chosen[0]]))
        await self.drain()
        self.version = chosen
        return chosen != (0, 0)

    # --- dispatch -----------------------------------------------------------

    async def dispatch(self, msg: ps.Structure) -> bool:
        sig = msg.tag
        if sig == M_GOODBYE:
            return False
        if sig == M_RESET:
            self.failed = False
            self._finish_bolt_trace("abandoned")
            username = self.interpreter.username
            self.interpreter.abort()
            self.interpreter = Interpreter(self.ictx)
            self.interpreter.username = username  # RESET keeps the identity
            self._prepared = None
            self.send_success()
            return True
        if self.failed and sig not in (M_RESET, M_GOODBYE):
            self.send(M_IGNORED)
            return True
        if not self.authenticated and sig not in (M_HELLO, M_LOGON):
            self.send_failure(
                "Memgraph.ClientError.Security.Unauthenticated",
                "authentication required before other requests")
            return True
        try:
            if sig == M_HELLO:
                return self.on_hello(msg.fields[0] if msg.fields else {})
            if sig == M_LOGON:
                return self.on_logon(msg.fields[0] if msg.fields else {})
            if sig == M_LOGOFF:
                self.authenticated = False
                self._unregister_session()
                self.send_success()
                return True
            if sig == M_RUN:
                return await self.on_run(*msg.fields)
            if sig == M_PULL:
                return await self.on_pull(
                    msg.fields[0] if msg.fields else {})
            if sig == M_DISCARD:
                return await self.on_discard(
                    msg.fields[0] if msg.fields else {})
            if sig == M_BEGIN:
                await self._offload(self.interpreter.execute, "BEGIN")
                self.send_success()
                return True
            if sig == M_COMMIT:
                await self._offload(self.interpreter.execute, "COMMIT")
                self.send_success({"bookmark": "mg-bookmark"})
                return True
            if sig == M_ROLLBACK:
                await self._offload(self.interpreter.execute, "ROLLBACK")
                self.send_success()
                return True
            if sig == M_ROUTE:
                return self.on_route(msg.fields)
            self.send_failure("Memgraph.ClientError.Request.Invalid",
                              f"unsupported message 0x{sig:02X}")
            return True
        except MemgraphTpuError as e:
            self.send_failure(self._error_code(e), str(e))
            return True
        except Exception as e:  # pragma: no cover - defensive
            log.exception("error handling bolt message")
            self.send_failure("Memgraph.DatabaseError.Generic.Unknown",
                              str(e))
            return True

    @staticmethod
    def _error_code(e: MemgraphTpuError) -> str:
        from ..exceptions import (SemanticException, SyntaxException,
                                  TransactionException)
        if isinstance(e, SyntaxException):
            return "Memgraph.ClientError.Statement.SyntaxError"
        if isinstance(e, SemanticException):
            return "Memgraph.ClientError.Statement.SemanticError"
        if isinstance(e, TransactionException):
            return "Memgraph.ClientError.Transaction.Invalid"
        return "Memgraph.TransientError.General.Error"

    # --- handlers -----------------------------------------------------------

    def on_hello(self, extra: dict) -> bool:
        if self.version >= (5, 1):
            # auth arrives via LOGON; only an instance with no users defined
            # may proceed unauthenticated
            self.authenticated = (self.auth is None
                                  or not self.auth.users())
        else:
            principal = extra.get("principal", "")
            credentials = extra.get("credentials", "")
            scheme = (extra.get("scheme") or "basic").lower()
            if self.auth is not None and scheme not in ("basic", "none"):
                username = self.auth.authenticate_external(
                    scheme, principal, credentials)
                if username is None:
                    self.send_failure(
                        "Memgraph.ClientError.Security.Unauthenticated",
                        f"authentication failure (scheme {scheme!r})")
                    return True
                self.authenticated = True
                self.interpreter.username = username
            elif self.auth is not None and not self.auth.authenticate(
                    principal, credentials):
                self.send_failure(
                    "Memgraph.ClientError.Security.Unauthenticated",
                    "authentication failure")
                return True
            else:
                self.authenticated = True
                self.interpreter.username = principal
        if self.authenticated and not self._register_or_refuse():
            return True
        server_name = (getattr(self.ictx, "config", {}) or {}).get(
            "bolt_server_name") or "Neo4j/5.2.0 compatible (memgraph-tpu)"
        self.send_success({
            "server": server_name,
            "connection_id": "bolt-1",
        })
        return True

    def on_logon(self, auth_data: dict) -> bool:
        principal = auth_data.get("principal", "")
        credentials = auth_data.get("credentials", "")
        scheme = (auth_data.get("scheme") or "basic").lower()
        if self.auth is not None and scheme != "basic" \
                and scheme != "none":
            # SSO/external scheme: routed through the mapped auth module
            # (reference: --auth-module-mappings, auth/module.hpp)
            username = self.auth.authenticate_external(
                scheme, principal, credentials)
            if username is None:
                self.send_failure(
                    "Memgraph.ClientError.Security.Unauthenticated",
                    f"authentication failure (scheme {scheme!r})")
                return True
            self.authenticated = True
            self.interpreter.username = username
            if not self._register_or_refuse():
                return True
            self.send_success({})
            return True
        if self.auth is not None and not self.auth.authenticate(
                principal, credentials):
            self.send_failure(
                "Memgraph.ClientError.Security.Unauthenticated",
                "authentication failure")
            return True
        self.authenticated = True
        self.interpreter.username = principal  # RBAC enforcement identity
        if not self._register_or_refuse():
            return True
        self.send_success()
        return True

    def _traced_call(self, fn, *args):
        """Run fn on the worker thread under the session's trace context
        (thread-local, so the activation must happen ON that thread)."""
        handle = self._bolt_trace
        if handle is None:
            return fn(*args)
        with mgtrace.activate(handle.ctx):
            return fn(*args)

    def _finish_bolt_trace(self, status: str = "ok") -> None:
        if self._bolt_trace is not None:
            self._bolt_trace.finish(status=status)
            self._bolt_trace = None

    async def on_run(self, query: str, parameters: dict = None,
                     extra: dict = None) -> bool:
        parameters = {k: bolt_to_value(v)
                      for k, v in (parameters or {}).items()}
        if mgtrace.armed():
            # the Bolt extra-metadata field is the trace carrier across
            # the client boundary: drivers propagate {"trace":
            # {trace_id, span_id, sampled}} and the whole server-side
            # trace joins the caller's
            self._finish_bolt_trace("abandoned")
            carrier = None
            if isinstance(extra, dict):
                carrier = extra.get("trace") or \
                    (extra.get("tx_metadata") or {}).get("trace")
            self._bolt_trace = mgtrace.begin_trace(
                "bolt.run", carrier if isinstance(carrier, dict) else None)
        import time as _time
        t0 = _time.perf_counter()
        prepared = await self._offload(self._traced_call,
                                       self.interpreter.prepare, query,
                                       parameters)
        from ..observability.metrics import global_metrics
        global_metrics.observe(
            "bolt.prepare_latency_sec", _time.perf_counter() - t0,
            trace_id=self._bolt_trace.trace_id
            if self._bolt_trace is not None else None)
        self._prepared = prepared
        meta = {"fields": prepared.columns, "t_first": 0, "qid": 0}
        if self._bolt_trace is not None:
            meta["trace_id"] = self._bolt_trace.trace_id
        self.send_success(meta)
        return True

    async def on_pull(self, extra: dict) -> bool:
        n = extra.get("n", -1)
        storage = self.interpreter.ctx.storage  # honors USE DATABASE
        from ..storage.common import View
        rows, has_more, summary = await self._offload(
            self.interpreter.pull, n)
        for row in rows:
            self.send(M_RECORD,
                      [value_to_bolt(v, storage, View.NEW, self.version)
                       for v in row])
        meta = {"has_more": has_more}
        if not has_more:
            meta["t_last"] = 0
            meta["type"] = self._prepared.summary_type if self._prepared \
                else "r"
            stats = summary.get("stats") if summary else None
            if stats and any(stats.values()):
                meta["stats"] = {k.replace("_", "-"): v
                                 for k, v in stats.items() if v}
            if self._bolt_trace is not None:
                meta["trace_id"] = self._bolt_trace.trace_id
                self._finish_bolt_trace("ok")
        self.send_success(meta)
        return True

    async def on_discard(self, extra: dict) -> bool:
        await self._offload(self.interpreter.pull, -1)
        self._finish_bolt_trace("ok")
        self.send_success({"has_more": False})
        return True

    def on_route(self, fields) -> bool:
        addr = self.ictx.config.get("advertised_address", "localhost:7687")
        coordinator = getattr(self.ictx, "coordinator", None)
        if coordinator is not None:
            # serve from LIVE replicated cluster state: MAIN writes,
            # replicas read, this coordinator routes (reference:
            # coordinator_instance.cpp; clients re-route to a surviving
            # coordinator after a failover)
            table = coordinator.route_table()
            servers = []
            if table["writers"]:
                servers.append({"addresses": table["writers"],
                                "role": "WRITE"})
            if table["readers"]:
                servers.append({"addresses": table["readers"],
                                "role": "READ"})
            servers.append(
                {"addresses": coordinator.routers or [addr],
                 "role": "ROUTE"})
            rt = {"ttl": 10, "db": "memgraph",
                  "epoch": table.get("epoch", 0),
                  "servers": servers}
            if table.get("shards"):
                # shard topology (r18, mgshard) rides the ROUTE reply
                # under the same fencing epoch as the writer table
                rt["shards"] = table["shards"]
            self.send_success({"rt": rt})
            return True
        # single-instance routing table: this server serves all roles
        self.send_success({"rt": {
            "ttl": 300,
            "db": "memgraph",
            "servers": [
                {"addresses": [addr], "role": "WRITE"},
                {"addresses": [addr], "role": "READ"},
                {"addresses": [addr], "role": "ROUTE"},
            ],
        }})
        return True

    async def refuse_overloaded(self) -> None:
        """Session-cap refusal: finish the handshake so the client can
        parse a real Bolt FAILURE (instead of a dead socket), send it,
        and hang up. The client sees a transient, retryable error."""
        try:
            if not await self.handshake():
                return
            # consume the client's HELLO first: sending FAILURE and
            # closing immediately can RST the client's in-flight HELLO
            # before it ever reads our refusal
            await self.read_message()
            self.send_failure(
                "Memgraph.TransientError.General.ServerOverloaded",
                "server overloaded: max concurrent sessions reached, "
                "retry later")
            await self.drain()
        except (asyncio.IncompleteReadError, ConnectionResetError,
                OSError):
            pass   # the refused peer vanished first; nothing to clean up
        finally:
            self.writer.close()


class BoltServer:
    """Asyncio TCP server accepting Bolt sessions."""

    def __init__(self, interpreter_context: InterpreterContext,
                 host: str = "127.0.0.1", port: int = 7687, auth=None,
                 ssl_context=None, workers: int = None,
                 max_sessions: int | None = None):
        self.ictx = interpreter_context
        self.host = host
        self.port = port
        self.auth = auth
        self.ssl_context = ssl_context   # bolt+s (ref: communication/context.cpp)
        # accept-loop backpressure (reference: --bolt-num-workers bounded
        # session pool): beyond max_sessions concurrent sessions, new
        # connections get a proper Bolt FAILURE ("server overloaded")
        # instead of unbounded accept → fd/thread exhaustion under a
        # connection storm. 0/None = unlimited (single-user default).
        if max_sessions is None:
            max_sessions = int(os.environ.get(
                "MEMGRAPH_TPU_BOLT_MAX_SESSIONS", 0))
        self.max_sessions = max_sessions
        self._live_sessions = 0      # only touched on the event loop
        self._server = None
        if workers is None:
            workers = min(32, (os.cpu_count() or 4) * 4)
        from concurrent.futures import ThreadPoolExecutor
        # deep generator chains (one Python frame per plan operator) are
        # heap-allocated and FOR_ITER_GEN-inlined on CPython 3.12 — no
        # native stack growth — so only sys.recursionlimit (raised by the
        # Interpreter) matters, not thread stack size
        self._executor = (ThreadPoolExecutor(
            max_workers=workers, thread_name_prefix="bolt-worker")
            if workers > 0 else None)

    async def _handle(self, reader, writer):
        from ..observability.metrics import global_metrics
        session = BoltSession(reader, writer, self.ictx, self.auth,
                              executor=self._executor)
        if self.max_sessions and self._live_sessions >= self.max_sessions:
            global_metrics.increment("bolt.connections_rejected_total")
            log.warning("bolt: refusing connection, %d/%d sessions live",
                        self._live_sessions, self.max_sessions)
            await session.refuse_overloaded()
            return
        self._live_sessions += 1
        # USE-style pool gauges for the saturation plane (GET /health):
        # live vs cap makes pool exhaustion machine-readable
        global_metrics.set_gauge("bolt.sessions_live",
                                 float(self._live_sessions))
        global_metrics.set_gauge("bolt.sessions_max",
                                 float(self.max_sessions or 0))
        try:
            await session.run()
        finally:
            self._live_sessions -= 1
            global_metrics.set_gauge("bolt.sessions_live",
                                     float(self._live_sessions))

    async def start(self):
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port, ssl=self.ssl_context)
        return self._server

    async def serve_forever(self):
        await self.start()
        async with self._server:
            await self._server.serve_forever()

    def stop(self) -> None:
        """Release the worker pool (and the listener if still open).

        `asyncio.Server.close()` is not thread-safe: calling it from a
        foreign thread races the loop thread's own `_wakeup` (a client
        disconnect closing the last transport) and dies with
        `TypeError: 'NoneType' object is not iterable`. When the
        server's loop is still running, the close is marshalled onto it
        with `call_soon_threadsafe`; a close that loses the race to an
        already-completed shutdown is logged and ignored."""
        if self._executor is not None:
            self._executor.shutdown(wait=False, cancel_futures=True)
        srv = self._server
        if srv is None:
            return

        def _close():
            try:
                srv.close()
            except (RuntimeError, TypeError) as e:
                log.debug("bolt: listener already closing: %s", e)

        try:
            loop = srv.get_loop()
        except (RuntimeError, AttributeError):
            loop = None
        if loop is not None and loop.is_running() and not loop.is_closed():
            loop.call_soon_threadsafe(_close)
        else:
            _close()

    def run_in_thread(self):
        """Start the server on a background thread; returns (thread, loop).

        Raises the underlying error (e.g. port in use) if startup fails.
        """
        import threading
        loop = asyncio.new_event_loop()
        started = threading.Event()
        startup_error: list = []

        def runner():
            asyncio.set_event_loop(loop)
            try:
                loop.run_until_complete(self.start())
            except Exception as e:
                startup_error.append(e)
                started.set()
                return
            started.set()
            loop.run_forever()

        thread = threading.Thread(target=runner, daemon=True)
        thread.start()
        if not started.wait(timeout=10):
            raise TimeoutError("bolt server failed to start within 10s")
        if startup_error:
            raise startup_error[0]
        return thread, loop
