"""Multiprocess read-query executor: a path past the GIL for OLTP reads.

The Bolt worker pool gives concurrency, not CPU parallelism — pure-
Python operator execution serializes on the GIL, so aggregate
multi-client read throughput plateaus at ~1x (README, measured r4).
This executor forks N worker processes, each inheriting a copy-on-write
snapshot of the storage; read-only queries fan out round-robin over
pipes and execute with N independent GILs.

Semantics: every worker serves the database AS OF the last fork().
`refresh()` re-forks after commits — the same snapshot-staleness
contract as the analytics GraphCache (ops/csr.py), applied to host
reads. Writes and transactional reads stay on the in-process path.

Caveats (documented, enforced):
  - queries that reach jax/device state are refused in workers (fork
    after CUDA/TPU init is unsafe); this pool is for host-path OLTP.
  - one core boxes (like this dev host) show ~1x: the component buys
    architecture; the speedup needs real cores.

Reference analog: the reference is a multithreaded C++ server with no
GIL to escape; this component restores multi-core reads for the Python
host layer.
"""

from __future__ import annotations

import itertools
import os
import pickle
import struct
import threading
import time

from ..observability import trace as mgtrace

__all__ = ["MPReadExecutor"]


def _send(fd, obj) -> None:
    data = pickle.dumps(obj)
    os.write(fd, struct.pack("<I", len(data)) + data)


def _recv(fd):
    hdr = b""
    while len(hdr) < 4:
        chunk = os.read(fd, 4 - len(hdr))
        if not chunk:
            raise EOFError
        hdr += chunk
    (n,) = struct.unpack("<I", hdr)
    buf = b""
    while len(buf) < n:
        chunk = os.read(fd, n - len(buf))
        if not chunk:
            raise EOFError
        buf += chunk
    return pickle.loads(buf)


class MPReadExecutor:
    def __init__(self, ictx, n_workers: int = 4) -> None:
        from ..observability.metrics import global_metrics
        self._ictx = ictx
        self._n = max(1, n_workers)
        self._workers: list = []       # (pid, req_fd, resp_fd)
        self._locks: list = []
        self._rr = itertools.count()
        # saturation plane: in-flight vs worker count = queue depth
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        global_metrics.set_gauge("mp_executor.workers", float(self._n))
        global_metrics.set_gauge("mp_executor.in_flight", 0.0)
        self._fork()

    # -- lifecycle ----------------------------------------------------------

    def _fork(self) -> None:
        self.close()
        self._workers = []
        self._locks = []
        for _ in range(self._n):
            self._workers.append(self._spawn_one())
            self._locks.append(threading.Lock())

    def _spawn_one(self) -> tuple:
        req_r, req_w = os.pipe()
        resp_r, resp_w = os.pipe()
        pid = os.fork()
        if pid == 0:                      # ---- child ----
            os.close(req_w)
            os.close(resp_r)
            try:
                self._worker_loop(req_r, resp_w)
            finally:
                os._exit(0)
        os.close(req_r)
        os.close(resp_w)
        return (pid, req_w, resp_r)

    def _respawn(self, i: int, dead) -> None:
        """Replace a crashed worker (caller holds ``self._locks[i]``):
        reap the corpse, fork a fresh worker off the CURRENT parent
        snapshot, and count the respawn so dashboards see churn."""
        from ..observability.metrics import global_metrics
        pid, req_fd, resp_fd = dead
        for fd in (req_fd, resp_fd):
            try:
                os.close(fd)
            except OSError:
                pass
        try:
            os.waitpid(pid, os.WNOHANG)
        except ChildProcessError:
            pass
        self._workers[i] = self._spawn_one()
        global_metrics.increment("mp_executor.worker_respawn_total")

    def _worker_loop(self, req_fd: int, resp_fd: int) -> None:
        from ..query import Interpreter
        from ..query.frontend import ast as A
        interp = Interpreter(self._ictx)
        refusal = ("QueryException",
                   "only read-only Cypher queries may run on the "
                   "multiprocess read executor (writes against the forked "
                   "snapshot would be silently lost)")
        while True:
            try:
                msg = _recv(req_fd)
            except (EOFError, OSError, struct.error, ValueError,
                    pickle.UnpicklingError):
                # torn/garbage frame on the request pipe: the parent
                # side is gone or corrupt — exit so the parent's
                # respawn path replaces this worker cleanly
                return
            if msg is None:
                return
            query, params, carrier = msg
            try:
                # enforce the read-only contract BEFORE prepare: non-Cypher
                # statements (auth/DDL/admin) can mutate state at prepare
                # time, and a misrouted write would vanish into this
                # worker's copy-on-write snapshot
                node = interp.ctx.cached_parse(query)
                if not isinstance(node, A.CypherQuery):
                    _send(resp_fd, ("err", *refusal))
                    continue
                # the job envelope is the trace carrier across the fork
                # boundary: this worker's spans (incl. the interpreter's
                # own query trace) join the parent's trace, then ship
                # home on the response envelope
                with mgtrace.adopt(carrier):
                    with mgtrace.span("mp.worker"):
                        prepared = interp.prepare(query, params)
                        if prepared.is_write:
                            interp.abort()
                            _send(resp_fd, ("err", *refusal))
                            continue
                        rows, _more, _summary = interp.pull(-1)
                spans = mgtrace.take_trace(carrier["trace_id"]) \
                    if carrier else []
                _send(resp_fd, ("ok", prepared.columns, rows, spans))
            except Exception as e:  # noqa: BLE001 — ship the error back
                try:
                    _send(resp_fd, ("err", type(e).__name__, str(e)))
                except (OSError, ValueError, struct.error):
                    return      # response pipe gone: die, get respawned

    def refresh(self) -> None:
        """Re-fork so workers see the current committed state."""
        self._fork()

    def close(self) -> None:
        for pid, req_fd, resp_fd in self._workers:
            try:
                _send(req_fd, None)
            except OSError:
                pass
            for fd in (req_fd, resp_fd):
                try:
                    os.close(fd)
                except OSError:
                    pass
            try:
                os.waitpid(pid, 0)
            except ChildProcessError:
                pass
        self._workers = []
        self._locks = []

    # -- execution ----------------------------------------------------------

    def execute(self, query: str, params: dict | None = None):
        """Round-robin a read-only query to a worker; returns
        (columns, rows). Worker-side errors are rehydrated into the
        typed taxonomy (SyntaxException stays SyntaxException across
        the fork boundary)."""
        from ..observability.metrics import global_metrics
        from ..observability.stats import global_query_stats
        if not self._workers:
            raise RuntimeError("executor is closed")
        i = next(self._rr) % len(self._workers)
        with self._inflight_lock:
            self._inflight += 1
            global_metrics.set_gauge("mp_executor.in_flight",
                                     float(self._inflight))
        t0 = time.perf_counter()
        try:
            with mgtrace.span("mp.execute", worker=i):
                with self._locks[i]:
                    # unpack INSIDE the lock: _respawn replaces the
                    # tuple under this same lock, and a pre-lock copy
                    # could name fds already closed AND reused by the
                    # replacement's pipes (framing corruption)
                    pid, req_fd, resp_fd = self._workers[i]
                    try:
                        _send(req_fd,
                              (query, params or {}, mgtrace.inject()))
                        out = _recv(resp_fd)
                    except (OSError, EOFError, struct.error,
                            ValueError, pickle.UnpicklingError) as e:
                        # dead worker: a wedged queue was the old
                        # failure mode — instead, respawn in place and
                        # fail THIS job with a typed retryable error
                        # (ConnectionError in the MRO: RetryPolicy's
                        # default retry_on catches it)
                        from ..exceptions import WorkerCrashedError
                        self._respawn(i, (pid, req_fd, resp_fd))
                        global_metrics.increment(
                            "mp_executor.errors_total")
                        global_query_stats.record_text(
                            query, time.perf_counter() - t0, rows=0,
                            error=True,
                            trace_id=mgtrace.current_trace_id())
                        raise WorkerCrashedError(
                            f"mp_executor worker {i} (pid {pid}) died "
                            "mid-request; respawned — retry") from e
        finally:
            with self._inflight_lock:
                self._inflight -= 1
                global_metrics.set_gauge("mp_executor.in_flight",
                                         float(self._inflight))
        if out[0] == "err":
            # worker-side stats die with the forked snapshot; the parent
            # registry is the authoritative fingerprint table, so the
            # routed query accounts HERE — errors included
            global_metrics.increment("mp_executor.errors_total")
            global_query_stats.record_text(
                query, time.perf_counter() - t0, rows=0, error=True,
                trace_id=mgtrace.current_trace_id())
            from ..exceptions import raise_wire_error
            raise_wire_error(out[1], out[2])
        if len(out) > 3:
            mgtrace.adopt_spans(out[3])
        global_query_stats.record_text(
            query, time.perf_counter() - t0, rows=len(out[2]),
            trace_id=mgtrace.current_trace_id())
        return out[1], out[2]
