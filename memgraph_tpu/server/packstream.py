"""PackStream serialization (Bolt's value format).

Counterpart of the reference's Bolt encoder/decoder
(/root/reference/src/communication/bolt/v1/encoder/, decoder/): the
PackStream v2 wire format used by Bolt 4.x/5.x — ints, floats, strings,
lists, maps, structs (Node/Relationship/Path/temporal/point), with the
v5 element-id fields.
"""

from __future__ import annotations

import struct
from io import BytesIO

from ..exceptions import MemgraphTpuError


class PackStreamError(MemgraphTpuError):
    pass


# struct tags
S_NODE = 0x4E
S_RELATIONSHIP = 0x52
S_UNBOUND_RELATIONSHIP = 0x72
S_PATH = 0x50
S_DATE = 0x44
S_TIME = 0x54
S_LOCAL_TIME = 0x74
S_DATETIME = 0x49          # v5 UTC datetime
S_DATETIME_ZONE_ID = 0x69  # v5 UTC datetime w/ zone name
S_LOCAL_DATETIME = 0x64
S_DURATION = 0x45
S_POINT_2D = 0x58
S_POINT_3D = 0x59


class Structure:
    __slots__ = ("tag", "fields")

    def __init__(self, tag: int, fields: list) -> None:
        self.tag = tag
        self.fields = fields

    def __eq__(self, other):
        return (isinstance(other, Structure) and other.tag == self.tag
                and other.fields == self.fields)

    def __repr__(self) -> str:  # pragma: no cover
        return f"Structure(0x{self.tag:02X}, {self.fields!r})"


def pack(value, buf: BytesIO | None = None) -> bytes:
    out = bytearray()
    _pack(value, out)
    if buf is not None:
        buf.write(bytes(out))
        return b""
    return bytes(out)


_pack_to = struct.pack


def _pack(v, out: bytearray) -> None:
    # bytearray appends, not BytesIO writes: bulk UNWIND parameters are
    # one huge nested list and the encoder runs per element
    if v is None:
        out.append(0xC0)
    elif v is True:
        out.append(0xC3)
    elif v is False:
        out.append(0xC2)
    elif isinstance(v, int):
        if -0x10 <= v < 0x80:
            out.append(v & 0xFF)
        elif -0x80 <= v < 0x80:
            out.append(0xC8)
            out.append(v & 0xFF)
        elif -0x8000 <= v < 0x8000:
            out.append(0xC9)
            out += v.to_bytes(2, "big", signed=True)
        elif -0x80000000 <= v < 0x80000000:
            out.append(0xCA)
            out += v.to_bytes(4, "big", signed=True)
        elif -0x8000000000000000 <= v < 0x8000000000000000:
            out.append(0xCB)
            out += v.to_bytes(8, "big", signed=True)
        else:
            raise PackStreamError(f"integer out of 64-bit range: {v}")
    elif isinstance(v, float):
        out.append(0xC1)
        out += _pack_to(">d", v)
    elif isinstance(v, str):
        raw = v.encode("utf-8")
        n = len(raw)
        if n < 0x10:
            out.append(0x80 | n)
        elif n < 0x100:
            out.append(0xD0)
            out.append(n)
        elif n < 0x10000:
            out.append(0xD1)
            out += _pack_to(">H", n)
        else:
            out.append(0xD2)
            out += _pack_to(">I", n)
        out += raw
    elif isinstance(v, bytes):
        n = len(v)
        if n < 0x100:
            out.append(0xCC)
            out.append(n)
        elif n < 0x10000:
            out.append(0xCD)
            out += _pack_to(">H", n)
        else:
            out.append(0xCE)
            out += _pack_to(">I", n)
        out += v
    elif isinstance(v, (list, tuple)):
        n = len(v)
        if n < 0x10:
            out.append(0x90 | n)
        elif n < 0x100:
            out.append(0xD4)
            out.append(n)
        elif n < 0x10000:
            out.append(0xD5)
            out += _pack_to(">H", n)
        else:
            out.append(0xD6)
            out += _pack_to(">I", n)
        for item in v:
            _pack(item, out)
    elif isinstance(v, dict):
        n = len(v)
        if n < 0x10:
            out.append(0xA0 | n)
        elif n < 0x100:
            out.append(0xD8)
            out.append(n)
        elif n < 0x10000:
            out.append(0xD9)
            out += _pack_to(">H", n)
        else:
            out.append(0xDA)
            out += _pack_to(">I", n)
        for key, val in v.items():
            _pack(str(key), out)
            _pack(val, out)
    elif isinstance(v, Structure):
        out.append(0xB0 | len(v.fields))
        out.append(v.tag)
        for f in v.fields:
            _pack(f, out)
    else:
        raise PackStreamError(f"cannot pack {type(v)!r}")


def _pack_int(v: int, out) -> None:
    """Kept for callers that encode bare ints; bytearray-based."""
    if isinstance(out, BytesIO):
        tmp = bytearray()
        _pack(v, tmp)
        out.write(bytes(tmp))
        return
    _pack(v, out)


_unpack_from = struct.unpack_from


def _unpack_at(data: bytes, pos: int):
    """Decode one value at `pos`; returns (value, next_pos). Flat function
    with direct byte indexing — the per-element method-call + slice +
    bounds-check of the old class decoder dominated bulk-parameter
    ingestion (10k-row UNWIND batches are one big nested list)."""
    marker = data[pos]
    pos += 1
    if marker < 0x80:
        return marker, pos
    if marker >= 0xF0:
        return marker - 0x100, pos
    if marker < 0x90:
        n = marker & 0x0F
        if pos + n > len(data):
            raise PackStreamError("unexpected end of data")
        return data[pos:pos + n].decode("utf-8"), pos + n
    if marker < 0xA0:
        out = []
        append = out.append
        for _ in range(marker & 0x0F):
            v, pos = _unpack_at(data, pos)
            append(v)
        return out, pos
    if marker < 0xB0:
        out = {}
        for _ in range(marker & 0x0F):
            k, pos = _unpack_at(data, pos)
            v, pos = _unpack_at(data, pos)
            out[k] = v
        return out, pos
    if marker < 0xC0:
        n = marker & 0x0F
        tag = data[pos]
        pos += 1
        fields = []
        for _ in range(n):
            v, pos = _unpack_at(data, pos)
            fields.append(v)
        return Structure(tag, fields), pos
    if marker == 0xC0:
        return None, pos
    if marker == 0xC1:
        return _unpack_from(">d", data, pos)[0], pos + 8
    if marker == 0xC2:
        return False, pos
    if marker == 0xC3:
        return True, pos
    if marker == 0xC8:
        return _unpack_from(">b", data, pos)[0], pos + 1
    if marker == 0xC9:
        return _unpack_from(">h", data, pos)[0], pos + 2
    if marker == 0xCA:
        return _unpack_from(">i", data, pos)[0], pos + 4
    if marker == 0xCB:
        return _unpack_from(">q", data, pos)[0], pos + 8
    if marker in (0xCC, 0xCD, 0xCE):
        if marker == 0xCC:
            n = data[pos]
            pos += 1
        elif marker == 0xCD:
            n = _unpack_from(">H", data, pos)[0]
            pos += 2
        else:
            n = _unpack_from(">I", data, pos)[0]
            pos += 4
        if pos + n > len(data):
            raise PackStreamError("unexpected end of data")
        return data[pos:pos + n], pos + n
    if marker in (0xD0, 0xD1, 0xD2):
        if marker == 0xD0:
            n = data[pos]
            pos += 1
        elif marker == 0xD1:
            n = _unpack_from(">H", data, pos)[0]
            pos += 2
        else:
            n = _unpack_from(">I", data, pos)[0]
            pos += 4
        if pos + n > len(data):
            raise PackStreamError("unexpected end of data")
        return data[pos:pos + n].decode("utf-8"), pos + n
    if marker in (0xD4, 0xD5, 0xD6):
        if marker == 0xD4:
            n = data[pos]
            pos += 1
        elif marker == 0xD5:
            n = _unpack_from(">H", data, pos)[0]
            pos += 2
        else:
            n = _unpack_from(">I", data, pos)[0]
            pos += 4
        out = []
        append = out.append
        for _ in range(n):
            v, pos = _unpack_at(data, pos)
            append(v)
        return out, pos
    if marker in (0xD8, 0xD9, 0xDA):
        if marker == 0xD8:
            n = data[pos]
            pos += 1
        elif marker == 0xD9:
            n = _unpack_from(">H", data, pos)[0]
            pos += 2
        else:
            n = _unpack_from(">I", data, pos)[0]
            pos += 4
        out = {}
        for _ in range(n):
            k, pos = _unpack_at(data, pos)
            v, pos = _unpack_at(data, pos)
            out[k] = v
        return out, pos
    raise PackStreamError(f"unknown marker 0x{marker:02X}")


class Unpacker:
    def __init__(self, data: bytes) -> None:
        self.data = data
        self.pos = 0

    def unpack(self):
        try:
            value, self.pos = _unpack_at(self.data, self.pos)
        except (IndexError, struct.error) as e:
            raise PackStreamError("unexpected end of data") from e
        return value


def unpack(data: bytes):
    return Unpacker(data).unpack()
