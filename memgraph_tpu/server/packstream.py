"""PackStream serialization (Bolt's value format).

Counterpart of the reference's Bolt encoder/decoder
(/root/reference/src/communication/bolt/v1/encoder/, decoder/): the
PackStream v2 wire format used by Bolt 4.x/5.x — ints, floats, strings,
lists, maps, structs (Node/Relationship/Path/temporal/point), with the
v5 element-id fields.
"""

from __future__ import annotations

import struct
from io import BytesIO

from ..exceptions import MemgraphTpuError


class PackStreamError(MemgraphTpuError):
    pass


# struct tags
S_NODE = 0x4E
S_RELATIONSHIP = 0x52
S_UNBOUND_RELATIONSHIP = 0x72
S_PATH = 0x50
S_DATE = 0x44
S_TIME = 0x54
S_LOCAL_TIME = 0x74
S_DATETIME = 0x49          # v5 UTC datetime
S_DATETIME_ZONE_ID = 0x69  # v5 UTC datetime w/ zone name
S_LOCAL_DATETIME = 0x64
S_DURATION = 0x45
S_POINT_2D = 0x58
S_POINT_3D = 0x59


class Structure:
    __slots__ = ("tag", "fields")

    def __init__(self, tag: int, fields: list) -> None:
        self.tag = tag
        self.fields = fields

    def __eq__(self, other):
        return (isinstance(other, Structure) and other.tag == self.tag
                and other.fields == self.fields)

    def __repr__(self) -> str:  # pragma: no cover
        return f"Structure(0x{self.tag:02X}, {self.fields!r})"


def pack(value, buf: BytesIO | None = None) -> bytes:
    out = buf or BytesIO()
    _pack(value, out)
    return out.getvalue() if buf is None else b""


def _pack(v, out: BytesIO) -> None:
    if v is None:
        out.write(b"\xC0")
    elif v is True:
        out.write(b"\xC3")
    elif v is False:
        out.write(b"\xC2")
    elif isinstance(v, int):
        _pack_int(v, out)
    elif isinstance(v, float):
        out.write(b"\xC1" + struct.pack(">d", v))
    elif isinstance(v, str):
        raw = v.encode("utf-8")
        n = len(raw)
        if n < 0x10:
            out.write(bytes((0x80 | n,)))
        elif n < 0x100:
            out.write(b"\xD0" + bytes((n,)))
        elif n < 0x10000:
            out.write(b"\xD1" + struct.pack(">H", n))
        else:
            out.write(b"\xD2" + struct.pack(">I", n))
        out.write(raw)
    elif isinstance(v, bytes):
        n = len(v)
        if n < 0x100:
            out.write(b"\xCC" + bytes((n,)))
        elif n < 0x10000:
            out.write(b"\xCD" + struct.pack(">H", n))
        else:
            out.write(b"\xCE" + struct.pack(">I", n))
        out.write(v)
    elif isinstance(v, (list, tuple)):
        n = len(v)
        if n < 0x10:
            out.write(bytes((0x90 | n,)))
        elif n < 0x100:
            out.write(b"\xD4" + bytes((n,)))
        elif n < 0x10000:
            out.write(b"\xD5" + struct.pack(">H", n))
        else:
            out.write(b"\xD6" + struct.pack(">I", n))
        for item in v:
            _pack(item, out)
    elif isinstance(v, dict):
        n = len(v)
        if n < 0x10:
            out.write(bytes((0xA0 | n,)))
        elif n < 0x100:
            out.write(b"\xD8" + bytes((n,)))
        elif n < 0x10000:
            out.write(b"\xD9" + struct.pack(">H", n))
        else:
            out.write(b"\xDA" + struct.pack(">I", n))
        for key, val in v.items():
            _pack(str(key), out)
            _pack(val, out)
    elif isinstance(v, Structure):
        n = len(v.fields)
        out.write(bytes((0xB0 | n, v.tag)))
        for f in v.fields:
            _pack(f, out)
    else:
        raise PackStreamError(f"cannot pack {type(v)!r}")


def _pack_int(v: int, out: BytesIO) -> None:
    if -0x10 <= v < 0x80:
        out.write(struct.pack(">b", v))
    elif -0x80 <= v < 0x80:
        out.write(b"\xC8" + struct.pack(">b", v))
    elif -0x8000 <= v < 0x8000:
        out.write(b"\xC9" + struct.pack(">h", v))
    elif -0x80000000 <= v < 0x80000000:
        out.write(b"\xCA" + struct.pack(">i", v))
    elif -0x8000000000000000 <= v < 0x8000000000000000:
        out.write(b"\xCB" + struct.pack(">q", v))
    else:
        raise PackStreamError(f"integer out of 64-bit range: {v}")


class Unpacker:
    def __init__(self, data: bytes) -> None:
        self.data = data
        self.pos = 0

    def _read(self, n: int) -> bytes:
        if self.pos + n > len(self.data):
            raise PackStreamError("unexpected end of data")
        out = self.data[self.pos:self.pos + n]
        self.pos += n
        return out

    def unpack(self):
        marker = self._read(1)[0]
        if marker < 0x80:
            return marker
        if marker >= 0xF0:
            return marker - 0x100
        if 0x80 <= marker < 0x90:
            return self._read(marker & 0x0F).decode("utf-8")
        if 0x90 <= marker < 0xA0:
            return [self.unpack() for _ in range(marker & 0x0F)]
        if 0xA0 <= marker < 0xB0:
            return {self.unpack(): self.unpack()
                    for _ in range(marker & 0x0F)}
        if 0xB0 <= marker < 0xC0:
            n = marker & 0x0F
            tag = self._read(1)[0]
            return Structure(tag, [self.unpack() for _ in range(n)])
        if marker == 0xC0:
            return None
        if marker == 0xC1:
            return struct.unpack(">d", self._read(8))[0]
        if marker == 0xC2:
            return False
        if marker == 0xC3:
            return True
        if marker == 0xC8:
            return struct.unpack(">b", self._read(1))[0]
        if marker == 0xC9:
            return struct.unpack(">h", self._read(2))[0]
        if marker == 0xCA:
            return struct.unpack(">i", self._read(4))[0]
        if marker == 0xCB:
            return struct.unpack(">q", self._read(8))[0]
        if marker == 0xCC:
            return self._read(self._read(1)[0])
        if marker == 0xCD:
            return self._read(struct.unpack(">H", self._read(2))[0])
        if marker == 0xCE:
            return self._read(struct.unpack(">I", self._read(4))[0])
        if marker == 0xD0:
            return self._read(self._read(1)[0]).decode("utf-8")
        if marker == 0xD1:
            return self._read(struct.unpack(">H", self._read(2))[0]) \
                .decode("utf-8")
        if marker == 0xD2:
            return self._read(struct.unpack(">I", self._read(4))[0]) \
                .decode("utf-8")
        if marker == 0xD4:
            return [self.unpack() for _ in range(self._read(1)[0])]
        if marker == 0xD5:
            return [self.unpack()
                    for _ in range(struct.unpack(">H", self._read(2))[0])]
        if marker == 0xD6:
            return [self.unpack()
                    for _ in range(struct.unpack(">I", self._read(4))[0])]
        if marker == 0xD8:
            return {self.unpack(): self.unpack()
                    for _ in range(self._read(1)[0])}
        if marker == 0xD9:
            return {self.unpack(): self.unpack()
                    for _ in range(struct.unpack(">H", self._read(2))[0])}
        if marker == 0xDA:
            return {self.unpack(): self.unpack()
                    for _ in range(struct.unpack(">I", self._read(4))[0])}
        raise PackStreamError(f"unknown marker 0x{marker:02X}")


def unpack(data: bytes):
    return Unpacker(data).unpack()
