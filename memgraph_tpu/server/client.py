"""Minimal synchronous Bolt client.

Counterpart of the reference's test/client bolt client
(/root/reference/src/communication/bolt/client.cpp): handshake, HELLO/LOGON,
RUN/PULL, explicit transactions. Used by the e2e tests and usable as a thin
Python driver for the server.
"""

from __future__ import annotations

import socket
import struct

from ..exceptions import MemgraphTpuError
from . import packstream as ps
from .bolt import (BOLT_MAGIC, M_BEGIN, M_COMMIT, M_GOODBYE, M_HELLO,
                   M_LOGON, M_PULL, M_RECORD, M_RESET, M_ROLLBACK,
                   M_ROUTE, M_RUN, M_SUCCESS, M_FAILURE, M_IGNORED)


class BoltClientError(MemgraphTpuError):
    def __init__(self, code, message):
        super().__init__(f"{code}: {message}")
        self.code = code


class BoltClient:
    def __init__(self, host="127.0.0.1", port=7687, username="",
                 password="", timeout=30.0, versions=None,
                 encrypted=False, ca_file=None, scheme="basic"):
        self.sock = socket.create_connection((host, port), timeout=timeout)
        if encrypted:  # bolt+s: TLS from the first byte
            from ..utils.tls import client_context
            # hostname verification on when a CA is pinned (end-user path)
            self.sock = client_context(ca_file).wrap_socket(
                self.sock, server_hostname=host)
        self._versions = versions or ((5, 2), (5, 0), (4, 4), (4, 3))
        self._handshake()
        self._hello(username, password, scheme)

    # --- wire ---------------------------------------------------------------

    def _handshake(self):
        proposals = b""
        for (maj, minor) in list(self._versions)[:4]:
            proposals += bytes([0, 0, minor, maj])
        while len(proposals) < 16:
            proposals += bytes([0, 0, 0, 0])
        self.sock.sendall(BOLT_MAGIC + proposals)
        chosen = self._recv_exact(4)
        self.version = (chosen[3], chosen[2])
        if self.version == (0, 0):
            raise MemgraphTpuError("bolt version negotiation failed")

    def _recv_exact(self, n: int) -> bytes:
        out = b""
        while len(out) < n:
            chunk = self.sock.recv(n - len(out))
            if not chunk:
                raise MemgraphTpuError("connection closed")
            out += chunk
        return out

    def _send_message(self, signature: int, *fields):
        data = ps.pack(ps.Structure(signature, list(fields)))
        msg = b""
        pos = 0
        while pos < len(data):
            chunk = data[pos:pos + 0xFFFF]
            msg += struct.pack(">H", len(chunk)) + chunk
            pos += len(chunk)
        self.sock.sendall(msg + b"\x00\x00")

    def _read_message(self) -> ps.Structure:
        chunks = []
        while True:
            size = struct.unpack(">H", self._recv_exact(2))[0]
            if size == 0:
                if chunks:
                    return ps.unpack(b"".join(chunks))
                continue
            chunks.append(self._recv_exact(size))

    def _expect_success(self) -> dict:
        msg = self._read_message()
        if msg.tag == M_SUCCESS:
            return msg.fields[0] if msg.fields else {}
        if msg.tag == M_FAILURE:
            meta = msg.fields[0]
            raise BoltClientError(meta.get("code", "?"),
                                  meta.get("message", "?"))
        if msg.tag == M_IGNORED:
            raise MemgraphTpuError("request ignored (session failed state)")
        raise MemgraphTpuError(f"unexpected message 0x{msg.tag:02X}")

    # --- protocol -----------------------------------------------------------

    def _hello(self, username, password, scheme="basic"):
        extra = {"user_agent": "memgraph-tpu-client/0.1"}
        if self.version < (5, 1):
            extra.update({"scheme": scheme, "principal": username,
                          "credentials": password})
        self._send_message(M_HELLO, extra)
        self._expect_success()
        if self.version >= (5, 1):
            self._send_message(M_LOGON, {"scheme": scheme,
                                         "principal": username,
                                         "credentials": password})
            self._expect_success()

    def execute(self, query: str, parameters: dict | None = None):
        """Run a query, pull everything. Returns (columns, rows, summary)."""
        self._send_message(M_RUN, query, parameters or {}, {})
        meta = self._expect_success()
        columns = meta.get("fields", [])
        rows = []
        while True:
            self._send_message(M_PULL, {"n": 1000})
            while True:
                msg = self._read_message()
                if msg.tag == M_RECORD:
                    rows.append(msg.fields[0])
                    continue
                if msg.tag == M_SUCCESS:
                    summary = msg.fields[0] if msg.fields else {}
                    break
                if msg.tag == M_FAILURE:
                    m = msg.fields[0]
                    raise BoltClientError(m.get("code", "?"),
                                          m.get("message", "?"))
                raise MemgraphTpuError(
                    f"unexpected message 0x{msg.tag:02X}")
            if not summary.get("has_more"):
                return columns, rows, summary

    def begin(self):
        self._send_message(M_BEGIN, {})
        self._expect_success()

    def commit(self):
        self._send_message(M_COMMIT)
        self._expect_success()

    def rollback(self):
        self._send_message(M_ROLLBACK)
        self._expect_success()

    def reset(self):
        self._send_message(M_RESET)
        self._expect_success()

    def route(self, routing: dict | None = None, db: str | None = None):
        """Fetch the routing table (Bolt 4.3+ ROUTE message)."""
        self._send_message(M_ROUTE, routing or {}, [], db)
        meta = self._expect_success()
        return meta.get("rt")

    def close(self):
        try:
            self._send_message(M_GOODBYE)
        except OSError:
            pass  # peer already gone; GOODBYE is best-effort
        self.sock.close()
