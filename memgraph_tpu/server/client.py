"""Minimal synchronous Bolt client.

Counterpart of the reference's test/client bolt client
(/root/reference/src/communication/bolt/client.cpp): handshake, HELLO/LOGON,
RUN/PULL, explicit transactions. Used by the e2e tests and usable as a thin
Python driver for the server.
"""

from __future__ import annotations

import socket
import struct

from ..exceptions import MemgraphTpuError
from . import packstream as ps
from .bolt import (BOLT_MAGIC, M_BEGIN, M_COMMIT, M_GOODBYE, M_HELLO,
                   M_LOGON, M_PULL, M_RECORD, M_RESET, M_ROLLBACK,
                   M_ROUTE, M_RUN, M_SUCCESS, M_FAILURE, M_IGNORED)


class BoltClientError(MemgraphTpuError):
    def __init__(self, code, message):
        super().__init__(f"{code}: {message}")
        self.code = code


class BoltClient:
    def __init__(self, host="127.0.0.1", port=7687, username="",
                 password="", timeout=30.0, versions=None,
                 encrypted=False, ca_file=None, scheme="basic"):
        self.sock = socket.create_connection((host, port), timeout=timeout)
        if encrypted:  # bolt+s: TLS from the first byte
            from ..utils.tls import client_context
            # hostname verification on when a CA is pinned (end-user path)
            self.sock = client_context(ca_file).wrap_socket(
                self.sock, server_hostname=host)
        self._versions = versions or ((5, 2), (5, 0), (4, 4), (4, 3))
        self._handshake()
        self._hello(username, password, scheme)

    # --- wire ---------------------------------------------------------------

    def _handshake(self):
        proposals = b""
        for (maj, minor) in list(self._versions)[:4]:
            proposals += bytes([0, 0, minor, maj])
        while len(proposals) < 16:
            proposals += bytes([0, 0, 0, 0])
        self.sock.sendall(BOLT_MAGIC + proposals)
        chosen = self._recv_exact(4)
        self.version = (chosen[3], chosen[2])
        if self.version == (0, 0):
            raise MemgraphTpuError("bolt version negotiation failed")

    def _recv_exact(self, n: int) -> bytes:
        out = b""
        while len(out) < n:
            chunk = self.sock.recv(n - len(out))
            if not chunk:
                raise MemgraphTpuError("connection closed")
            out += chunk
        return out

    def _send_message(self, signature: int, *fields):
        data = ps.pack(ps.Structure(signature, list(fields)))
        msg = b""
        pos = 0
        while pos < len(data):
            chunk = data[pos:pos + 0xFFFF]
            msg += struct.pack(">H", len(chunk)) + chunk
            pos += len(chunk)
        self.sock.sendall(msg + b"\x00\x00")

    def _read_message(self) -> ps.Structure:
        chunks = []
        while True:
            size = struct.unpack(">H", self._recv_exact(2))[0]
            if size == 0:
                if chunks:
                    return ps.unpack(b"".join(chunks))
                continue
            chunks.append(self._recv_exact(size))

    def _expect_success(self) -> dict:
        msg = self._read_message()
        if msg.tag == M_SUCCESS:
            return msg.fields[0] if msg.fields else {}
        if msg.tag == M_FAILURE:
            meta = msg.fields[0]
            raise BoltClientError(meta.get("code", "?"),
                                  meta.get("message", "?"))
        if msg.tag == M_IGNORED:
            raise MemgraphTpuError("request ignored (session failed state)")
        raise MemgraphTpuError(f"unexpected message 0x{msg.tag:02X}")

    # --- protocol -----------------------------------------------------------

    def _hello(self, username, password, scheme="basic"):
        extra = {"user_agent": "memgraph-tpu-client/0.1"}
        if self.version < (5, 1):
            extra.update({"scheme": scheme, "principal": username,
                          "credentials": password})
        self._send_message(M_HELLO, extra)
        self._expect_success()
        if self.version >= (5, 1):
            self._send_message(M_LOGON, {"scheme": scheme,
                                         "principal": username,
                                         "credentials": password})
            self._expect_success()

    def execute(self, query: str, parameters: dict | None = None):
        """Run a query, pull everything. Returns (columns, rows, summary)."""
        self._send_message(M_RUN, query, parameters or {}, {})
        meta = self._expect_success()
        columns = meta.get("fields", [])
        rows = []
        while True:
            self._send_message(M_PULL, {"n": 1000})
            while True:
                msg = self._read_message()
                if msg.tag == M_RECORD:
                    rows.append(msg.fields[0])
                    continue
                if msg.tag == M_SUCCESS:
                    summary = msg.fields[0] if msg.fields else {}
                    break
                if msg.tag == M_FAILURE:
                    m = msg.fields[0]
                    raise BoltClientError(m.get("code", "?"),
                                          m.get("message", "?"))
                raise MemgraphTpuError(
                    f"unexpected message 0x{msg.tag:02X}")
            if not summary.get("has_more"):
                return columns, rows, summary

    def begin(self):
        self._send_message(M_BEGIN, {})
        self._expect_success()

    def commit(self):
        self._send_message(M_COMMIT)
        self._expect_success()

    def rollback(self):
        self._send_message(M_ROLLBACK)
        self._expect_success()

    def reset(self):
        self._send_message(M_RESET)
        self._expect_success()

    def route(self, routing: dict | None = None, db: str | None = None):
        """Fetch the routing table (Bolt 4.3+ ROUTE message)."""
        self._send_message(M_ROUTE, routing or {}, [], db)
        meta = self._expect_success()
        return meta.get("rt")

    def close(self):
        try:
            self._send_message(M_GOODBYE)
        except OSError:
            pass  # peer already gone; GOODBYE is best-effort
        self.sock.close()


class RoutedClient:
    """Route-table-driven writes with failover retry.

    A thin HA driver over :class:`BoltClient` (reference analog: the
    neo4j driver's routing table handling against coordinators): it
    bootstraps from one or more router (coordinator) addresses, fetches
    the ROUTE table, and sends writes to the current writer. On any
    failure it refreshes the table — from ANY reachable router learned
    so far — and retries against the (possibly new) MAIN with
    exponential backoff, so a failover is a handful of retried requests
    instead of an error surfaced to the caller.

    Fencing: the table carries the coordinator's fencing epoch; the
    client remembers the highest epoch it has seen and refuses to go
    back to a table (or writer) from an older one — a partitioned
    coordinator serving a stale table cannot steer writes to a deposed
    MAIN.
    """

    def __init__(self, routers: list[str], username: str = "",
                 password: str = "", retry=None, timeout: float = 10.0):
        from ..utils.retry import RetryPolicy
        if not routers:
            raise MemgraphTpuError("RoutedClient needs >= 1 router")
        self.routers = list(routers)
        self.username = username
        self.password = password
        # the RetryPolicy owns ALL timing: per-connection timeout rides
        # attempt_timeout (the legacy `timeout` arg seeds it), and an
        # optional policy deadline bounds a whole routed write
        self.retry = retry or RetryPolicy(base_delay=0.2, max_delay=2.0,
                                          max_retries=8,
                                          attempt_timeout=timeout)
        self.timeout = self.retry.attempt_timeout \
            if self.retry.attempt_timeout is not None else timeout
        self.known_epoch = 0
        self._writer_addr: str | None = None
        self._writer: BoltClient | None = None
        # shard topology (r18, mgshard): shard_id -> owner endpoint,
        # refreshed with the writer table under the SAME epoch guard —
        # a stale coordinator can never roll the shard map backwards
        self.shard_table: dict[int, str] = {}

    @staticmethod
    def _split(addr: str) -> tuple[str, int]:
        host, _, port = addr.rpartition(":")
        return host, int(port)

    def refresh_route_table(self) -> bool:
        """Fetch a fresh table from any reachable router; keep only a
        table at least as new (by fencing epoch) as what we know."""
        for router in list(self.routers):
            host, port = self._split(router)
            try:
                rc = BoltClient(host=host, port=port,
                                username=self.username,
                                password=self.password,
                                timeout=self.timeout)
            except (OSError, MemgraphTpuError):
                continue
            try:
                rt = rc.route() or {}
            except (OSError, MemgraphTpuError):
                continue
            finally:
                try:
                    rc.close()
                except OSError:
                    pass
            epoch = int(rt.get("epoch") or 0)
            if epoch < self.known_epoch:
                continue   # stale coordinator (partitioned minority)
            self.known_epoch = max(self.known_epoch, epoch)
            if rt.get("shards"):
                self.shard_table = {int(k): v
                                    for k, v in rt["shards"].items()}
            servers = {s["role"]: s["addresses"]
                       for s in rt.get("servers", [])}
            for r in servers.get("ROUTE", []):
                if r not in self.routers:
                    self.routers.append(r)
            writers = servers.get("WRITE", [])
            if writers:
                if writers[0] != self._writer_addr:
                    self._disconnect()
                    self._writer_addr = writers[0]
                return True
        return False

    def _disconnect(self) -> None:
        if self._writer is not None:
            try:
                self._writer.close()
            except OSError:
                pass
            self._writer = None

    def _connect_writer(self) -> BoltClient:
        if self._writer is None:
            if self._writer_addr is None and not self.refresh_route_table():
                raise MemgraphTpuError("no writer in any routing table")
            host, port = self._split(self._writer_addr)
            self._writer = BoltClient(host=host, port=port,
                                      username=self.username,
                                      password=self.password,
                                      timeout=self.timeout)
        return self._writer

    def execute_write(self, query: str, parameters: dict | None = None):
        """Run a write on the current MAIN, re-routing with backoff on
        failure. Returns (columns, rows, summary) like BoltClient.

        Timing is RetryPolicy-owned: `attempts()` sleeps the backoff
        between tries and stops early when the policy's overall deadline
        would be crossed — no ad-hoc sleep/timeout constants here."""
        last: Exception | None = None
        for _attempt in self.retry.attempts():
            try:
                return self._connect_writer().execute(query, parameters)
            except BoltClientError as e:
                if e.code.startswith(("Memgraph.ClientError.Statement",
                                      "Memgraph.ClientError.Security")):
                    raise   # the query/auth is wrong; rerouting won't help
                # transaction/transient failures (fenced main, strict
                # replicas unavailable mid-failover) ARE the retry case
                last = e
                self._disconnect()
                self.refresh_route_table()
            except (OSError, MemgraphTpuError) as e:
                last = e
                self._disconnect()
                self.refresh_route_table()
        raise MemgraphTpuError(
            f"write failed after {self.retry.max_retries + 1} routed "
            f"attempts: {last}") from last

    def close(self) -> None:
        self._disconnect()
