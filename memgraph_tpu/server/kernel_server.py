"""Resident kernel server: keeps one JAX/TPU runtime warm for
short-lived client processes — now a SUPERVISED service.

Measured on the tunneled axon platform (NOTES_ROUND4): every fresh
process pays ~1.5s to load the device executable stack before its first
kernel dispatch — which dominated CALL-to-first-record latency for CLI
tools and bench stages. The production server process (memgraph_tpu.main)
is naturally resident; this daemon gives every OTHER process the same
property: a unix-socket service holding the device runtime, compiled
kernels, and graph caches, so a cold client's first CALL costs one
socket round-trip plus device compute.

Resilience (r12) — device failure is a first-class, typed, recoverable
event end to end:

  * every dispatch returns a TYPED outcome: completed /
    deadline_exceeded / device_error / oom / shed / invalid. Clients
    raise matching exception types (AdmissionRejected, KernelOom, ...)
    so callers branch on class, not message text;
  * a per-request ``deadline_s`` bounds how long a client waits on the
    device — the dispatch runs on a worker thread, and a device hang
    yields a prompt ``deadline_exceeded`` instead of a wedged client;
  * an HBM ADMISSION GUARD estimates each request's device footprint
    against a budget and sheds (typed, counted, loudly logged) instead
    of letting one oversized request OOM the resident runtime for
    everyone;
  * compute routes through the RESUMABLE mesh entry points
    (parallel/analytics.py): long pagerank runs checkpoint every k
    iterations, so a mid-run device fault costs ≤ k redone iterations;
  * :class:`SupervisedKernelClient` is the client-side supervisor:
    idempotent requests retry under a shared RetryPolicy (per-attempt
    timeout + overall deadline), a health-check loop watches the
    daemon's ``health`` op, and a WEDGED (dispatch overdue) or LOST
    (device.lost killed the process) server is restarted;
  * everything is counted through observability.metrics — the server's
    own counters ride the ``health`` reply across the process boundary.

Protocol (local trusted unix socket): length-prefixed frames, each a
JSON header {op, arrays: [{name, dtype, shape}], ...params} followed by
the raw array bytes in order. Ops: ping, health, probe, pagerank,
shutdown.

Reference analog: none directly — the reference is a resident C++
daemon by construction (src/memgraph.cpp); this component restores that
property for out-of-process analytics callers.
"""

from __future__ import annotations

import json
import logging
import os
import signal
import socket
import struct
import subprocess
import sys
import time

import numpy as np

from ..observability import stats as mgstats
from ..observability import trace as mgtrace
from ..observability.metrics import global_metrics
from ..utils.devicefault import classify_device_error, device_fault_point
from ..utils.retry import RetryPolicy

log = logging.getLogger(__name__)

DEFAULT_SOCKET = os.environ.get(
    "MEMGRAPH_TPU_KERNEL_SERVER_SOCKET",
    os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), ".kernel_server.sock"))

#: typed per-dispatch outcomes (the taxonomy tests assert against)
DISPATCH_OUTCOMES = ("completed", "deadline_exceeded", "device_error",
                     "oom", "shed", "invalid")


def _resolve_hbm_budget() -> int:
    """Admission budget: env override, else 75% of the device's reported
    byte limit, else a conservative 4 GiB."""
    env = os.environ.get("MEMGRAPH_TPU_HBM_BUDGET_BYTES")
    if env:
        try:
            return int(env)
        except ValueError:
            log.warning("bad MEMGRAPH_TPU_HBM_BUDGET_BYTES=%r; ignoring",
                        env)
    try:
        import jax
        stats = jax.devices()[0].memory_stats() or {}
        limit = int(stats.get("bytes_limit") or 0)
        if limit > 0:
            return int(limit * 0.75)
    except Exception as e:  # noqa: BLE001 — backends without memory_stats
        log.debug("no device memory stats (%s); using default budget", e)
    return 4 << 30


def _resolve_checkpoint_every() -> int:
    try:
        return max(0, int(os.environ.get(
            "MEMGRAPH_TPU_CHECKPOINT_EVERY", "16")))
    except ValueError:
        return 16


def _estimate_request_bytes(header: dict, arrays: dict) -> int:
    """Request HBM footprint estimate: the wire arrays land on device in
    up to 3 forms (COO staging, CSC copy, per-edge multipliers) plus
    ~8 O(n) float vectors of iteration state."""
    edge_bytes = sum(int(np.prod(a.shape, dtype=np.int64))
                     * a.dtype.itemsize for a in arrays.values())
    n_nodes = int(header.get("n_nodes") or 0)
    return 3 * edge_bytes + n_nodes * 4 * 8


def probe_device():
    """Tiny end-to-end device check: a compiled matmul with a host
    transfer forcing completion. Shared by the server warm-up, the
    ``probe`` op, and bench.py's probe stage — and guarded by the
    device fault point so probe failures are injectable too.
    Returns (checksum, platform)."""
    device_fault_point()
    import jax
    import jax.numpy as jnp
    x = jnp.ones((128, 128), jnp.float32)
    return float((x @ x).sum()), jax.devices()[0].platform


# --------------------------------------------------------------------------
# typed client errors (one per server outcome)
# --------------------------------------------------------------------------


class KernelServerError(RuntimeError):
    """Base kernel-server failure; carries the typed outcome."""

    def __init__(self, message: str, outcome: str = "invalid",
                 retryable: bool = False) -> None:
        super().__init__(message)
        self.outcome = outcome
        self.retryable = retryable


class AdmissionRejected(KernelServerError):
    """The HBM admission guard shed this request (outcome "shed").
    Deliberately NOT retryable: the same request against the same budget
    sheds again — resize the request or raise the budget."""

    def __init__(self, message: str) -> None:
        super().__init__(message, outcome="shed", retryable=False)


class KernelOom(KernelServerError):
    """Device memory exhausted during dispatch (outcome "oom")."""

    def __init__(self, message: str) -> None:
        super().__init__(message, outcome="oom", retryable=False)


class KernelDeviceError(KernelServerError):
    """Device-side dispatch failure (outcome "device_error"); the op is
    pure, so idempotent retry is safe."""

    def __init__(self, message: str) -> None:
        super().__init__(message, outcome="device_error", retryable=True)


class KernelDeadlineExceeded(KernelServerError):
    """The dispatch missed its deadline (outcome "deadline_exceeded") —
    possibly a wedged device; the supervisor health-checks on this."""

    def __init__(self, message: str) -> None:
        super().__init__(message, outcome="deadline_exceeded",
                         retryable=True)


_OUTCOME_ERRORS = {
    "shed": AdmissionRejected,
    "oom": KernelOom,
    "device_error": KernelDeviceError,
    "deadline_exceeded": KernelDeadlineExceeded,
}


def _raise_for_reply(header: dict):
    outcome = header.get("outcome", "invalid")
    cls = _OUTCOME_ERRORS.get(outcome)
    msg = header.get("error", "kernel server error")
    if cls is not None:
        raise cls(msg)
    raise KernelServerError(msg, outcome=outcome,
                            retryable=bool(header.get("retryable")))


# --------------------------------------------------------------------------
# framing
# --------------------------------------------------------------------------

def _send_msg(sock: socket.socket, header: dict,
              arrays: dict[str, np.ndarray] | None = None) -> None:
    arrays = arrays or {}
    header = dict(header)
    header["arrays"] = [
        {"name": k, "dtype": str(v.dtype), "shape": list(v.shape)}
        for k, v in arrays.items()]
    hb = json.dumps(header).encode("utf-8")
    parts = [struct.pack("<I", len(hb)), hb]
    for v in arrays.values():
        parts.append(np.ascontiguousarray(v).tobytes())
    sock.sendall(b"".join(parts))


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return bytes(buf)


def _recv_msg(sock: socket.socket):
    (hlen,) = struct.unpack("<I", _recv_exact(sock, 4))
    header = json.loads(_recv_exact(sock, hlen))
    arrays = {}
    for spec in header.pop("arrays", []):
        dt = np.dtype(spec["dtype"])
        count = int(np.prod(spec["shape"], dtype=np.int64)) if spec["shape"] \
            else 1
        raw = _recv_exact(sock, count * dt.itemsize)
        arrays[spec["name"]] = np.frombuffer(raw, dtype=dt).reshape(
            spec["shape"])
    return header, arrays


# --------------------------------------------------------------------------
# server
# --------------------------------------------------------------------------

class KernelServer:
    """One thread per connection; device dispatch serialized by a lock
    (one chip — concurrent kernels would just queue anyway). Every
    dispatch runs on a worker thread under a per-request deadline: a
    wedged device costs the caller a typed ``deadline_exceeded``, never
    a silent hang, and the ``health`` op exposes the overdue dispatch so
    the client-side supervisor can restart the process."""

    def __init__(self, socket_path: str = DEFAULT_SOCKET,
                 idle_timeout_s: float = 0.0,
                 hbm_budget_bytes: int | None = None,
                 checkpoint_every: int | None = None,
                 wedge_after_s: float | None = None) -> None:
        import threading
        self.socket_path = socket_path
        self.idle_timeout_s = idle_timeout_s
        self.hbm_budget_bytes = hbm_budget_bytes \
            if hbm_budget_bytes is not None else _resolve_hbm_budget()
        self.checkpoint_every = checkpoint_every \
            if checkpoint_every is not None else _resolve_checkpoint_every()
        self.wedge_after_s = wedge_after_s if wedge_after_s is not None \
            else float(os.environ.get(
                "MEMGRAPH_TPU_KS_WEDGE_AFTER_S", "60"))
        self._graphs: dict = {}      # graph_key -> DeviceGraph
        from ..utils.locks import tracked_lock
        from ..utils.sanitize import shared_field
        self._dispatch_lock = tracked_lock("KernelServer._dispatch_lock")
        self._shutdown = threading.Event()
        # written by every connection thread, read by the accept loop's
        # idle-timeout check — a leaf lock, never held across dispatch
        self._activity_lock = tracked_lock("KernelServer._activity_lock")
        self._last_activity = time.monotonic()
        # dispatch bookkeeping for the health op — a leaf lock too: the
        # health reply must never wait behind a wedged dispatch
        self._stats_lock = tracked_lock("KernelServer._stats_lock")
        self._active: dict[int, tuple[float, float | None]] = {}
        self._dispatch_seq = 0
        self._graphs_cached = 0
        self._started = time.monotonic()
        self._platform = "unknown"
        self._sock_ino = None        # inode of OUR bound socket path
        shared_field(self, "_graphs", "_last_activity", "_active",
                     "_dispatch_seq", "_graphs_cached", "_platform")
        # saturation plane: the admission budget is a bounded resource —
        # export it so capacity planning can see utilization vs limit
        global_metrics.set_gauge("kernel_server.hbm_budget_bytes",
                                 float(self.hbm_budget_bytes))

    def _touch_activity(self) -> None:
        from ..utils.sanitize import shared_write
        with self._activity_lock:
            shared_write(self, "_last_activity")
            self._last_activity = time.monotonic()

    def _idle_for(self) -> float:
        from ..utils.sanitize import shared_read
        with self._activity_lock:
            shared_read(self, "_last_activity")
            return time.monotonic() - self._last_activity

    def _warm(self) -> None:
        """Touch the device so the first client request pays no init."""
        from ..utils.sanitize import shared_write
        _, platform = probe_device()
        with self._stats_lock:
            shared_write(self, "_platform")
            self._platform = platform

    def serve_forever(self) -> None:
        import errno
        import threading

        # Spawn-race discipline (ADVICE r5): never unlink-before-bind.
        # A live responder on the path means another daemon already won —
        # exit and let clients use it. Only a provably-stale path (connect
        # refused) is unlinked, and shutdown unlinks only while the inode
        # still belongs to THIS server, so a losing daemon's exit can
        # never orphan the winner's socket.
        try:
            probe = KernelClient(self.socket_path, timeout=5.0)
            alive = probe.ping()
            probe.close()
            if alive:
                return           # already running; we lost the race
        except OSError:
            pass                 # nothing listening (or no socket yet)
        srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        try:
            srv.bind(self.socket_path)
        except OSError as e:
            if e.errno != errno.EADDRINUSE:
                raise
            # path exists but nobody answered the probe: stale socket
            # from a crashed daemon — reclaim it
            try:
                os.unlink(self.socket_path)
            except OSError:
                pass
            srv.bind(self.socket_path)
        try:
            self._sock_ino = os.stat(self.socket_path).st_ino
        except OSError:
            self._sock_ino = None
        srv.listen(8)
        self._warm()
        self._touch_activity()
        srv.settimeout(1.0)
        while not self._shutdown.is_set():
            try:
                conn, _ = srv.accept()
            except socket.timeout:
                if self.idle_timeout_s and \
                        self._idle_for() > self.idle_timeout_s:
                    break
                continue
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()
        srv.close()
        try:
            if self._sock_ino is not None and \
                    os.stat(self.socket_path).st_ino == self._sock_ino:
                os.unlink(self.socket_path)
        except OSError:
            pass

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            while not self._shutdown.is_set():
                try:
                    header, arrays = _recv_msg(conn)
                except (ConnectionError, struct.error, OSError):
                    return
                self._touch_activity()
                op = header.get("op")
                try:
                    if op == "ping":
                        _send_msg(conn, {"ok": True, "pid": os.getpid()})
                    elif op == "health":
                        _send_msg(conn, self._health_reply())
                    elif op == "shutdown":
                        _send_msg(conn, {"ok": True})
                        self._shutdown.set()
                        return
                    elif op in ("pagerank", "semiring", "probe"):
                        # supervised: admission guard + worker thread +
                        # per-request deadline; the reply ships AFTER
                        # the dispatch lock is released — a slow client
                        # must not hold up other clients' dispatches
                        reply, out_arrays = self._supervised(op, header,
                                                             arrays)
                        _send_msg(conn, reply, out_arrays)
                    else:
                        _send_msg(conn, {"ok": False, "outcome": "invalid",
                                         "error": f"unknown op {op!r}"})
                except Exception as e:  # noqa: BLE001 — report, continue
                    try:
                        _send_msg(conn, {"ok": False, "outcome": "invalid",
                                         "error": str(e)})
                    except OSError:
                        return
        finally:
            conn.close()

    # --- supervised dispatch ----------------------------------------------

    def _count(self, outcome: str) -> None:
        global_metrics.increment(f"kernel_server.dispatch.{outcome}_total")

    def _supervised(self, op: str, header: dict, arrays: dict):
        """Admission guard → worker-thread dispatch → typed outcome."""
        import threading
        from ..utils.sanitize import shared_write

        est = _estimate_request_bytes(header, arrays)
        if est > self.hbm_budget_bytes:
            self._count("shed")
            global_metrics.increment(
                "kernel_server.admission_rejected_total")
            log.warning(
                "kernel_server: SHED %s request — estimated footprint "
                "%d bytes exceeds HBM budget %d bytes", op, est,
                self.hbm_budget_bytes)
            return ({"ok": False, "outcome": "shed", "retryable": False,
                     "error": f"AdmissionRejected: estimated footprint "
                              f"{est} bytes exceeds HBM budget "
                              f"{self.hbm_budget_bytes} bytes"}, None)

        deadline_s = header.get("deadline_s")
        deadline_s = float(deadline_s) if deadline_s else None
        # trace carrier off the request protocol: the dispatch (and the
        # device stages under it) joins the caller's trace; its spans
        # ship home on the reply (take_trace below)
        carrier = header.pop("trace", None)
        with self._stats_lock:
            shared_write(self, "_dispatch_seq")
            self._dispatch_seq += 1
            did = self._dispatch_seq
            self._active[did] = (time.monotonic(),
                                 deadline_s or self.wedge_after_s)
            global_metrics.set_gauge("kernel_server.in_flight",
                                     float(len(self._active)))
        box: dict = {}
        t_dispatch = time.perf_counter()

        def work():
            try:
                # the activation is thread-local; the worker thread must
                # adopt the remote context itself. The stage accumulator
                # collects this dispatch's device attribution (transfer/
                # compile/iterate splits from the mesh entry points);
                # its snapshot ships home in the reply header so the
                # CALLER's PROFILE sees where the HBM-seconds went.
                acc = mgstats.StageAccumulator()
                with mgstats.collecting_stages(acc):
                    with mgtrace.adopt(carrier):
                        with mgtrace.span("kernel.dispatch", op=op,
                                          pid=os.getpid()):
                            with self._dispatch_lock:
                                device_fault_point()
                                box["result"] = self._dispatch_op(
                                    op, header, arrays)
                box["stages"] = acc.snapshot()
            except BaseException as e:  # noqa: BLE001 — classified below
                box["exc"] = e
            finally:
                with self._stats_lock:
                    shared_write(self, "_active")
                    self._active.pop(did, None)
                    global_metrics.set_gauge(
                        "kernel_server.in_flight",
                        float(len(self._active)))

        def ship_trace(reply: dict) -> dict:
            """Attach this dispatch's spans + stage splits + latency."""
            global_metrics.observe(
                "kernel_server.dispatch_latency_sec",
                time.perf_counter() - t_dispatch,
                trace_id=(carrier or {}).get("trace_id"))
            if carrier and carrier.get("trace_id"):
                spans = mgtrace.take_trace(carrier["trace_id"])
                if spans:
                    reply["trace_spans"] = spans
            stages = box.get("stages")
            if stages:
                reply["stages"] = stages
            return reply

        t = threading.Thread(target=work, daemon=True,
                             name=f"ks-dispatch-{did}")
        t.start()
        t.join(deadline_s)
        if t.is_alive():
            # the dispatch is overdue; it stays in _active, so the
            # health op reports the server as wedged until it finishes
            self._count("deadline_exceeded")
            log.warning("kernel_server: dispatch %d (%s) exceeded its "
                        "%.3fs deadline — device possibly wedged",
                        did, op, deadline_s)
            return ({"ok": False, "outcome": "deadline_exceeded",
                     "retryable": True,
                     "error": f"dispatch exceeded {deadline_s}s "
                              "deadline"}, None)
        if "exc" in box:
            e = box["exc"]
            kind = classify_device_error(e)
            if kind == "oom":
                outcome, retryable = "oom", False
            elif kind in ("device_error", "device_lost"):
                outcome, retryable = "device_error", True
            else:
                outcome, retryable = "invalid", False
            self._count(outcome)
            log.warning("kernel_server: dispatch %d (%s) failed "
                        "[%s]: %s", did, op, outcome, e)
            return (ship_trace({"ok": False, "outcome": outcome,
                                "retryable": retryable,
                                "error": f"{type(e).__name__}: {e}"}),
                    None)
        reply, out_arrays = box["result"]
        if reply.get("ok", True):
            reply.setdefault("outcome", "completed")
            self._count("completed")
        else:
            reply.setdefault("outcome", "invalid")
            self._count("invalid")
        return ship_trace(reply), out_arrays

    def _dispatch_op(self, op: str, header: dict, arrays: dict):
        """Runs under _dispatch_lock on the worker thread."""
        if op == "probe":
            checksum, platform = probe_device()
            return ({"ok": True, "platform": platform,
                     "sum": checksum}, None)
        if op == "semiring":
            return self._op_semiring(header, arrays)
        return self._op_pagerank(header, arrays)

    def _health_reply(self) -> dict:
        """Liveness + wedge detection + counters; NEVER touches the
        dispatch lock (a wedged dispatch must not wedge health)."""
        from ..utils.sanitize import shared_read
        now = time.monotonic()
        with self._stats_lock:
            shared_read(self, "_active")
            entries = list(self._active.values())
            cached = self._graphs_cached
            platform = self._platform
        ages = [now - t0 for t0, _dl in entries]
        wedged = any(dl is not None and now - t0 > dl
                     for t0, dl in entries)
        counters = {name: value for name, _kind, value
                    in global_metrics.snapshot()
                    if name.startswith(("kernel_server.", "analytics."))}
        return {"ok": True, "pid": os.getpid(),
                "uptime_s": round(now - self._started, 3),
                "in_flight": len(entries),
                "oldest_dispatch_s": round(max(ages, default=0.0), 3),
                "wedged": wedged,
                "graphs_cached": cached,
                "hbm_budget_bytes": self.hbm_budget_bytes,
                "checkpoint_every": self.checkpoint_every,
                "wedge_after_s": self.wedge_after_s,
                "platform": platform,
                "counters": counters}

    MAX_CACHED_GRAPHS = 8     # LRU cap: the daemon is long-lived and a
    #                           DeviceGraph pins device HBM + host arrays

    def _resolve_graph(self, header, arrays):
        """Graph-key LRU lookup / edge-array import shared by every
        graph-shaped op. Runs under _dispatch_lock (see _op_pagerank).
        Returns a DeviceGraph or None (caller replies invalid)."""
        from ..ops.csr import from_coo
        from ..utils.sanitize import shared_write
        key = header.get("graph_key")
        # mglint: disable=MG006 — the dispatcher (_supervised worker) holds _dispatch_lock across this whole handler; intraprocedural analysis cannot see caller locks
        g = self._graphs.pop(key, None) if key else None
        if g is not None:
            self._graphs[key] = g              # re-insert: LRU refresh
        if g is None:
            if "src" not in arrays:
                return None
            g = from_coo(arrays["src"].astype(np.int64),
                         arrays["dst"].astype(np.int64),
                         arrays.get("weights"),
                         n_nodes=header.get("n_nodes")).to_device()
            if key:
                # mglint: disable=MG006,MG007 — same _dispatch_lock contract as above: the LRU insert+evict runs under the dispatcher's lock
                self._graphs[key] = g
                while len(self._graphs) > self.MAX_CACHED_GRAPHS:  # mglint: disable=MG006 — under caller's _dispatch_lock
                    self._graphs.pop(next(iter(self._graphs)))  # mglint: disable=MG006,MG007 — under caller's _dispatch_lock
                with self._stats_lock:
                    shared_write(self, "_graphs_cached")
                    self._graphs_cached = len(self._graphs)  # mglint: disable=MG006 — len snapshot for health; insert path holds _dispatch_lock
        return g

    def _op_pagerank(self, header, arrays):
        """Runs under _dispatch_lock; returns (reply_header,
        reply_arrays) for the caller to ship outside the lock. Routes
        through the RESUMABLE mesh entry point (mesh-of-1 unless
        MEMGRAPH_TPU_MESH_DEVICES configures a wider mesh), so a device
        fault mid-run redoes at most checkpoint_every iterations."""
        from ..ops import semiring as S
        from ..parallel import analytics
        from ..parallel.mesh import analytics_mesh, get_mesh_context
        g = self._resolve_graph(header, arrays)
        if g is None:
            return ({"ok": False, "error": "unknown graph_key "
                     "and no edge arrays supplied"}, None)
        key = header.get("graph_key")
        ctx = analytics_mesh() or get_mesh_context(1)
        with S.backend_extent("mesh"):
            ranks, err, iters = analytics.pagerank_mesh(
                g, ctx, damping=header.get("damping", 0.85),
                max_iterations=header.get("max_iterations", 100),
                tol=header.get("tol", 1e-6),
                precision=header.get("precision", "f32"),
                checkpoint_every=self.checkpoint_every,
                job=f"kernel_server:pagerank:{key}" if key else None)
        return ({"ok": True, "err": float(err), "iters": int(iters)},
                {"ranks": np.asarray(ranks, dtype=np.float32)})

    def _op_semiring(self, header, arrays):
        """Semiring-core dispatch: run a named core-routed algorithm at
        a requested precision through the resident runtime.  Currently
        serves `pagerank` (plus-times, any precision — the bench's
        stage_semiring sweep) and `bfs` (min-plus levels via the
        GENERIC mesh semiring kernel).  Runs under _dispatch_lock."""
        from ..ops import semiring as S
        from ..parallel import analytics
        from ..parallel.mesh import analytics_mesh, get_mesh_context
        g = self._resolve_graph(header, arrays)
        if g is None:
            return ({"ok": False, "error": "unknown graph_key "
                     "and no edge arrays supplied"}, None)
        algorithm = header.get("algorithm", "pagerank")
        precision = header.get("precision", "f32")
        max_iterations = header.get("max_iterations", 100)
        if algorithm == "pagerank":
            from ..ops.pagerank import pagerank
            # ops-level entry: route_backend picks mesh/mxu/segment and
            # records the per-backend stage the PROFILE plane shows
            ranks, err, iters = pagerank(
                g, damping=header.get("damping", 0.85),
                max_iterations=max_iterations,
                tol=header.get("tol", 1e-6), precision=precision)
            return ({"ok": True, "err": float(err), "iters": int(iters),
                     "algorithm": algorithm, "precision": precision},
                    {"ranks": np.asarray(ranks, dtype=np.float32)})
        if algorithm == "bfs":
            ctx = analytics_mesh() or get_mesh_context(1)
            with S.backend_extent("mesh"):
                levels, iters = analytics.bfs_mesh(
                    g, ctx, int(header.get("source", 0)),
                    max_iterations=max_iterations, precision=precision,
                    checkpoint_every=self.checkpoint_every)
            return ({"ok": True, "iters": int(iters),
                     "algorithm": algorithm, "precision": precision},
                    {"levels": np.asarray(levels, dtype=np.int32)})
        return ({"ok": False,
                 "error": f"unknown semiring algorithm {algorithm!r}"},
                None)


# --------------------------------------------------------------------------
# client
# --------------------------------------------------------------------------

class KernelClient:
    def __init__(self, socket_path: str = DEFAULT_SOCKET,
                 timeout: float = 300.0) -> None:
        self.socket_path = socket_path
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.settimeout(timeout)
        self._sock.connect(socket_path)

    def settimeout(self, timeout: float | None) -> None:
        self._sock.settimeout(timeout)

    def call(self, header: dict, arrays=None):
        _send_msg(self._sock, header, arrays)
        h, out = _recv_msg(self._sock)
        # spans the server recorded for OUR trace come home on the
        # reply; adopt them so the retained trace is connected
        spans = h.pop("trace_spans", None)
        if spans:
            mgtrace.adopt_spans(spans)
        # same for the dispatch's device-stage splits: merge into the
        # caller's active stage accumulator (PROFILE attribution)
        mgstats.merge_stages(h.pop("stages", None))
        return h, out

    def ping(self) -> bool:
        try:
            h, _ = self.call({"op": "ping"})
            return bool(h.get("ok"))
        except (OSError, ConnectionError):
            return False

    def health(self) -> dict:
        h, _ = self.call({"op": "health"})
        return h

    def probe(self) -> dict:
        """Typed device probe through the resident runtime."""
        header = {"op": "probe"}
        carrier = mgtrace.inject()
        if carrier is not None:
            header["trace"] = carrier
        h, _ = self.call(header)
        return h

    def pagerank(self, src=None, dst=None, weights=None, n_nodes=None,
                 graph_key=None, deadline_s=None, **params):
        arrays = {}
        if src is not None:
            arrays["src"] = np.asarray(src, dtype=np.int64)
            arrays["dst"] = np.asarray(dst, dtype=np.int64)
            if weights is not None:
                arrays["weights"] = np.asarray(weights, dtype=np.float32)
        header = {"op": "pagerank", "graph_key": graph_key,
                  "n_nodes": n_nodes, **params}
        if deadline_s is not None:
            header["deadline_s"] = deadline_s
        carrier = mgtrace.inject()
        if carrier is not None:
            header["trace"] = carrier
        h, out = self.call(header, arrays)
        if not h.get("ok"):
            _raise_for_reply(h)
        return out["ranks"], h["err"], h["iters"]

    def semiring(self, algorithm: str = "pagerank", src=None, dst=None,
                 weights=None, n_nodes=None, graph_key=None,
                 precision: str = "f32", deadline_s=None, **params):
        """Run a semiring-core-routed algorithm on the resident daemon.
        Returns the reply header + arrays dict (algorithm-shaped:
        pagerank -> ranks/err/iters, bfs -> levels/iters)."""
        arrays = {}
        if src is not None:
            arrays["src"] = np.asarray(src, dtype=np.int64)
            arrays["dst"] = np.asarray(dst, dtype=np.int64)
            if weights is not None:
                arrays["weights"] = np.asarray(weights, dtype=np.float32)
        header = {"op": "semiring", "algorithm": algorithm,
                  "graph_key": graph_key, "n_nodes": n_nodes,
                  "precision": precision, **params}
        if deadline_s is not None:
            header["deadline_s"] = deadline_s
        carrier = mgtrace.inject()
        if carrier is not None:
            header["trace"] = carrier
        h, out = self.call(header, arrays)
        if not h.get("ok"):
            _raise_for_reply(h)
        return h, out

    def shutdown(self) -> None:
        try:
            self.call({"op": "shutdown"})
        except (OSError, ConnectionError):
            pass

    def close(self) -> None:
        self._sock.close()


# --------------------------------------------------------------------------
# client-side supervisor
# --------------------------------------------------------------------------

class SupervisedKernelClient:
    """Supervised access to the resident kernel server.

    Wraps :class:`KernelClient` with the client half of the resilience
    contract:

      * requests carry a per-request ``deadline_s`` and retry under a
        shared :class:`RetryPolicy` (per-attempt timeout + overall
        deadline) — but ONLY idempotent ones; non-idempotent calls
        surface the first typed failure;
      * connection loss (the daemon died — e.g. device.lost killed it)
        respawns the server via :func:`ensure_server` and retries;
      * ``check_once()`` (and the optional background health loop)
        polls the ``health`` op and RESTARTS a wedged or unreachable
        server process — SIGKILL + respawn; the daemon's stale-socket
        reclaim logic makes that safe;
      * typed non-retryable outcomes (AdmissionRejected, KernelOom)
        propagate immediately.
    """

    def __init__(self, socket_path: str = DEFAULT_SOCKET,
                 retry: RetryPolicy | None = None,
                 spawn_timeout_s: float = 120.0,
                 idle_timeout_s: float = 900.0,
                 deadline_s: float | None = None,
                 spawn: bool = True) -> None:
        import threading
        from ..utils.locks import tracked_lock
        from ..utils.sanitize import shared_field
        self.socket_path = socket_path
        self.retry = retry or RetryPolicy(
            base_delay=0.2, max_delay=2.0, max_retries=4,
            attempt_timeout=300.0)
        self.spawn_timeout_s = spawn_timeout_s
        self.idle_timeout_s = idle_timeout_s
        self.deadline_s = deadline_s
        self.spawn = spawn
        # leaf lock guarding the (client, pid) pair: swapped by the
        # caller thread AND the health loop; network I/O always happens
        # OUTSIDE it
        self._state_lock = tracked_lock("SupervisedKernelClient._state_lock")
        self._client: KernelClient | None = None
        self._pid: int | None = None
        self._stop = threading.Event()
        self._health_thread = None
        shared_field(self, "_client", "_pid")

    # --- connection management ---------------------------------------------

    def _install(self, client: KernelClient | None):
        from ..utils.sanitize import shared_write
        with self._state_lock:
            shared_write(self, "_client")
            old, self._client = self._client, client
        if old is not None:
            try:
                old.close()
            except OSError as e:
                log.debug("closing stale kernel client: %s", e)
        return client

    def _current(self) -> KernelClient | None:
        from ..utils.sanitize import shared_read
        with self._state_lock:
            shared_read(self, "_client")
            return self._client

    def _set_pid(self, pid: int | None) -> None:
        from ..utils.sanitize import shared_write
        with self._state_lock:
            shared_write(self, "_pid")
            self._pid = pid

    def _get_pid(self) -> int | None:
        from ..utils.sanitize import shared_read
        with self._state_lock:
            shared_read(self, "_pid")
            return self._pid

    def _connect(self) -> KernelClient:
        c = self._current()
        if c is not None:
            return c
        timeout = self.retry.attempt_timeout or 300.0
        if self.spawn:
            c = ensure_server(self.socket_path,
                              spawn_timeout_s=self.spawn_timeout_s,
                              idle_timeout_s=self.idle_timeout_s)
            if c is None:
                raise ConnectionError(
                    "kernel server spawn starved (no responder within "
                    f"{self.spawn_timeout_s}s)")
            c.settimeout(timeout)
        else:
            c = KernelClient(self.socket_path, timeout=timeout)
        try:
            h, _ = c.call({"op": "ping"})
            self._set_pid(h.get("pid"))
        except (OSError, ConnectionError) as e:
            log.debug("post-connect ping failed: %s", e)
        return self._install(c)

    def _drop(self) -> None:
        self._install(None)

    # --- supervision --------------------------------------------------------

    def health(self, timeout: float = 5.0) -> dict | None:
        """The daemon's health reply over a FRESH connection (a wedged
        request stream must not block the health probe), or None when
        nothing answers."""
        try:
            c = KernelClient(self.socket_path, timeout=timeout)
        except OSError:
            return None
        try:
            return c.health()
        except (OSError, ConnectionError):
            return None
        finally:
            try:
                c.close()
            except OSError as e:
                log.debug("closing health probe connection: %s", e)

    def _mirror_daemon_counters(self, h: dict) -> None:
        """Publish the daemon's health-reply counters through the LOCAL
        global Metrics registry so the supervisor's prometheus_text()
        carries them (restarts, sheds, deadline_exceeded, oom, ...) —
        not only callers of the ``health`` op. Gauges, not counters:
        they mirror another process's monotonic state and must not
        double-count across supervision rounds."""
        for name, value in (h.get("counters") or {}).items():
            short = name[len("kernel_server."):] \
                if name.startswith("kernel_server.") else name
            global_metrics.set_gauge(f"kernel_server.daemon.{short}",
                                     float(value))
        global_metrics.set_gauge("kernel_server.daemon.in_flight",
                                 float(h.get("in_flight", 0)))
        global_metrics.set_gauge("kernel_server.daemon.wedged",
                                 1.0 if h.get("wedged") else 0.0)

    def check_once(self) -> str:
        """One supervision round: health-check, restart when wedged or
        unreachable. Returns "ok" or "restarted"."""
        global_metrics.increment(
            "kernel_server.supervisor.health_checks_total")
        h = self.health()
        if h is None:
            self.restart_server(reason="unreachable")
            return "restarted"
        self._mirror_daemon_counters(h)
        if h.get("wedged"):
            global_metrics.increment(
                "kernel_server.supervisor.wedge_detected_total")
            self.restart_server(reason="wedged", pid=h.get("pid"))
            return "restarted"
        self._set_pid(h.get("pid"))
        return "ok"

    def restart_server(self, reason: str = "manual",
                       pid: int | None = None) -> None:
        """Kill the (wedged / device-lost) daemon and let the next call
        respawn it. The daemon's probe-then-bind + stale-socket reclaim
        makes the SIGKILL safe: the successor reclaims the path."""
        pid = pid or self._get_pid()
        self._drop()
        self._set_pid(None)
        if pid and pid != os.getpid():
            try:
                os.kill(pid, signal.SIGKILL)
            except (OSError, ProcessLookupError) as e:
                log.debug("kernel server pid %s already gone: %s", pid, e)
        global_metrics.increment("kernel_server.supervisor.restarts_total")
        log.warning("kernel_server supervisor: restarting server "
                    "(reason=%s pid=%s)", reason, pid)

    def start_health_loop(self, interval_s: float = 5.0) -> None:
        """Background supervision: health-check every interval_s,
        restarting a wedged/lost daemon. Idempotent."""
        import threading
        if self._health_thread is not None:
            return

        def loop():
            while not self._stop.wait(interval_s):
                try:
                    self.check_once()
                except Exception:  # noqa: BLE001 — supervision must survive
                    log.exception("kernel_server supervisor health "
                                  "check failed")

        self._health_thread = threading.Thread(
            target=loop, daemon=True, name="ks-supervisor")
        self._health_thread.start()

    # --- supervised calls ---------------------------------------------------

    def pagerank(self, src=None, dst=None, weights=None, n_nodes=None,
                 graph_key=None, idempotent: bool = True,
                 deadline_s: float | None = None, **params):
        """PageRank with supervised retries. Pure computation ⇒
        idempotent by default; callers piping through side-effecting
        wrappers pass idempotent=False and get fail-fast semantics."""
        if deadline_s is None:
            deadline_s = self.deadline_s
        last: Exception | None = None
        for _attempt in self.retry.attempts():
            try:
                c = self._connect()
                t0 = time.perf_counter()
                with mgtrace.span("kernel.request", op="pagerank",
                                  attempt=_attempt):
                    result = c.pagerank(src=src, dst=dst, weights=weights,
                                        n_nodes=n_nodes,
                                        graph_key=graph_key,
                                        deadline_s=deadline_s, **params)
                # client-observed dispatch wall time (request + device +
                # reply) for the caller's PROFILE attribution
                mgstats.record_stage("kernel_dispatch",
                                     time.perf_counter() - t0)
                return result
            except (AdmissionRejected, KernelOom):
                # deterministic against this budget/graph: retry is noise
                raise
            except KernelDeadlineExceeded as e:
                last = e
                if not idempotent:
                    raise
                global_metrics.increment(
                    "kernel_server.client.retries_total")
                self.check_once()    # a wedged server gets restarted here
            except KernelDeviceError as e:
                last = e
                if not idempotent:
                    raise
                global_metrics.increment(
                    "kernel_server.client.retries_total")
            except (ConnectionError, OSError) as e:
                # daemon gone (device.lost kill) or socket timed out:
                # drop the connection; _connect respawns when allowed
                last = e
                self._drop()
                if not idempotent:
                    raise
                global_metrics.increment(
                    "kernel_server.client.retries_total")
        raise KernelServerError(
            f"kernel request failed after {self.retry.max_retries + 1} "
            f"supervised attempts: {last}",
            outcome=getattr(last, "outcome", "invalid"),
            retryable=False) from last

    def close(self) -> None:
        self._stop.set()
        if self._health_thread is not None:
            self._health_thread.join(timeout=10)
            self._health_thread = None
        self._drop()


def ensure_server(socket_path: str = DEFAULT_SOCKET,
                  spawn_timeout_s: float = 120.0,
                  idle_timeout_s: float = 900.0):
    """Connect to the resident server, spawning it if absent.

    Returns a connected KernelClient, or None when the spawn TIMED OUT
    (the stillborn daemon is killed so it cannot keep competing for
    CPU). A daemon that DIED during init raises RuntimeError — that is
    a real regression, not an environmental condition, and callers'
    skip/fallback paths must not mask it."""
    try:
        c = KernelClient(socket_path, timeout=spawn_timeout_s)
        if c.ping():
            return c
        c.close()
    except OSError:
        pass
    proc = subprocess.Popen(
        [sys.executable, "-m", "memgraph_tpu.server.kernel_server",
         "--socket", socket_path, "--idle-timeout", str(idle_timeout_s)],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        start_new_session=True)   # survives the spawning client
    deadline = time.monotonic() + spawn_timeout_s
    while time.monotonic() < deadline:
        # keep polling the socket even if OUR child died: in a spawn
        # race the loser exits after probing a live responder (or on the
        # bind conflict) while the winner is still importing jax — its
        # server arrives soon
        try:
            c = KernelClient(socket_path, timeout=spawn_timeout_s)
            if c.ping():
                return c
            c.close()
        except OSError:
            time.sleep(0.1)
    if proc.poll() is not None:
        # nobody ever served AND our daemon died: a real init failure
        # (import error, crash), not environmental starvation
        raise RuntimeError(
            f"kernel server died during init (rc={proc.returncode})")
    try:
        proc.kill()               # a starved spawn must not linger
        proc.wait(timeout=10)
    except (OSError, subprocess.TimeoutExpired):
        pass
    return None


def main() -> None:
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--socket", default=DEFAULT_SOCKET)
    ap.add_argument("--idle-timeout", type=float, default=900.0)
    args = ap.parse_args()
    from ..utils.jax_cache import honor_jax_platforms_env
    honor_jax_platforms_env()
    KernelServer(args.socket, idle_timeout_s=args.idle_timeout).serve_forever()


if __name__ == "__main__":
    main()
