"""Resident kernel server: keeps one JAX/TPU runtime warm for
short-lived client processes.

Measured on the tunneled axon platform (NOTES_ROUND4): every fresh
process pays ~1.5s to load the device executable stack before its first
kernel dispatch — which dominated CALL-to-first-record latency for CLI
tools and bench stages. The production server process (memgraph_tpu.main)
is naturally resident; this daemon gives every OTHER process the same
property: a unix-socket service holding the device runtime, compiled
kernels, and graph caches, so a cold client's first CALL costs one
socket round-trip plus device compute.

Protocol (local trusted unix socket): length-prefixed frames, each a
JSON header {op, arrays: [{name, dtype, shape}], ...params} followed by
the raw array bytes in order. Ops: ping, pagerank, shutdown.

Reference analog: none directly — the reference is a resident C++
daemon by construction (src/memgraph.cpp); this component restores that
property for out-of-process analytics callers.
"""

from __future__ import annotations

import json
import os
import socket
import struct
import subprocess
import sys
import time

import numpy as np

DEFAULT_SOCKET = os.environ.get(
    "MEMGRAPH_TPU_KERNEL_SERVER_SOCKET",
    os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), ".kernel_server.sock"))


# --------------------------------------------------------------------------
# framing
# --------------------------------------------------------------------------

def _send_msg(sock: socket.socket, header: dict,
              arrays: dict[str, np.ndarray] | None = None) -> None:
    arrays = arrays or {}
    header = dict(header)
    header["arrays"] = [
        {"name": k, "dtype": str(v.dtype), "shape": list(v.shape)}
        for k, v in arrays.items()]
    hb = json.dumps(header).encode("utf-8")
    parts = [struct.pack("<I", len(hb)), hb]
    for v in arrays.values():
        parts.append(np.ascontiguousarray(v).tobytes())
    sock.sendall(b"".join(parts))


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return bytes(buf)


def _recv_msg(sock: socket.socket):
    (hlen,) = struct.unpack("<I", _recv_exact(sock, 4))
    header = json.loads(_recv_exact(sock, hlen))
    arrays = {}
    for spec in header.pop("arrays", []):
        dt = np.dtype(spec["dtype"])
        count = int(np.prod(spec["shape"], dtype=np.int64)) if spec["shape"] \
            else 1
        raw = _recv_exact(sock, count * dt.itemsize)
        arrays[spec["name"]] = np.frombuffer(raw, dtype=dt).reshape(
            spec["shape"])
    return header, arrays


# --------------------------------------------------------------------------
# server
# --------------------------------------------------------------------------

class KernelServer:
    """One thread per connection; device dispatch serialized by a lock
    (one chip — concurrent kernels would just queue anyway)."""

    def __init__(self, socket_path: str = DEFAULT_SOCKET,
                 idle_timeout_s: float = 0.0) -> None:
        import threading
        self.socket_path = socket_path
        self.idle_timeout_s = idle_timeout_s
        self._graphs: dict = {}      # graph_key -> DeviceGraph
        from ..utils.locks import tracked_lock
        from ..utils.sanitize import shared_field
        self._dispatch_lock = tracked_lock("KernelServer._dispatch_lock")
        self._shutdown = threading.Event()
        # written by every connection thread, read by the accept loop's
        # idle-timeout check — a leaf lock, never held across dispatch
        self._activity_lock = tracked_lock("KernelServer._activity_lock")
        self._last_activity = time.monotonic()
        self._sock_ino = None        # inode of OUR bound socket path
        shared_field(self, "_graphs", "_last_activity")

    def _touch_activity(self) -> None:
        from ..utils.sanitize import shared_write
        with self._activity_lock:
            shared_write(self, "_last_activity")
            self._last_activity = time.monotonic()

    def _idle_for(self) -> float:
        from ..utils.sanitize import shared_read
        with self._activity_lock:
            shared_read(self, "_last_activity")
            return time.monotonic() - self._last_activity

    def _warm(self) -> None:
        """Touch the device so the first client request pays no init."""
        import jax
        import jax.numpy as jnp
        x = jnp.ones((128, 128), jnp.float32)
        float((x @ x).sum())

    def serve_forever(self) -> None:
        import errno
        import threading

        # Spawn-race discipline (ADVICE r5): never unlink-before-bind.
        # A live responder on the path means another daemon already won —
        # exit and let clients use it. Only a provably-stale path (connect
        # refused) is unlinked, and shutdown unlinks only while the inode
        # still belongs to THIS server, so a losing daemon's exit can
        # never orphan the winner's socket.
        try:
            probe = KernelClient(self.socket_path, timeout=5.0)
            alive = probe.ping()
            probe.close()
            if alive:
                return           # already running; we lost the race
        except OSError:
            pass                 # nothing listening (or no socket yet)
        srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        try:
            srv.bind(self.socket_path)
        except OSError as e:
            if e.errno != errno.EADDRINUSE:
                raise
            # path exists but nobody answered the probe: stale socket
            # from a crashed daemon — reclaim it
            try:
                os.unlink(self.socket_path)
            except OSError:
                pass
            srv.bind(self.socket_path)
        try:
            self._sock_ino = os.stat(self.socket_path).st_ino
        except OSError:
            self._sock_ino = None
        srv.listen(8)
        self._warm()
        self._touch_activity()
        srv.settimeout(1.0)
        while not self._shutdown.is_set():
            try:
                conn, _ = srv.accept()
            except socket.timeout:
                if self.idle_timeout_s and \
                        self._idle_for() > self.idle_timeout_s:
                    break
                continue
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()
        srv.close()
        try:
            if self._sock_ino is not None and \
                    os.stat(self.socket_path).st_ino == self._sock_ino:
                os.unlink(self.socket_path)
        except OSError:
            pass

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            while not self._shutdown.is_set():
                try:
                    header, arrays = _recv_msg(conn)
                except (ConnectionError, struct.error, OSError):
                    return
                self._touch_activity()
                op = header.get("op")
                try:
                    if op == "ping":
                        _send_msg(conn, {"ok": True, "pid": os.getpid()})
                    elif op == "shutdown":
                        _send_msg(conn, {"ok": True})
                        self._shutdown.set()
                        return
                    elif op == "pagerank":
                        # device compute under the dispatch lock; the
                        # reply ships AFTER release — a slow client must
                        # not hold up every other client's dispatch
                        with self._dispatch_lock:
                            reply, out_arrays = self._op_pagerank(
                                header, arrays)
                        _send_msg(conn, reply, out_arrays)
                    else:
                        _send_msg(conn, {"ok": False,
                                         "error": f"unknown op {op!r}"})
                except Exception as e:  # noqa: BLE001 — report, continue
                    try:
                        _send_msg(conn, {"ok": False, "error": str(e)})
                    except OSError:
                        return
        finally:
            conn.close()

    MAX_CACHED_GRAPHS = 8     # LRU cap: the daemon is long-lived and a
    #                           DeviceGraph pins device HBM + host arrays

    def _op_pagerank(self, header, arrays):
        """Runs under _dispatch_lock; returns (reply_header,
        reply_arrays) for the caller to ship outside the lock."""
        from ..ops import pagerank as pr
        from ..ops.csr import from_coo
        key = header.get("graph_key")
        # mglint: disable=MG006 — the dispatcher (_serve_conn) holds _dispatch_lock across this whole handler; intraprocedural analysis cannot see caller locks
        g = self._graphs.pop(key, None) if key else None
        if g is not None:
            self._graphs[key] = g              # re-insert: LRU refresh
        if g is None:
            if "src" not in arrays:
                return ({"ok": False, "error": "unknown graph_key "
                         "and no edge arrays supplied"}, None)
            g = from_coo(arrays["src"].astype(np.int64),
                         arrays["dst"].astype(np.int64),
                         arrays.get("weights"),
                         n_nodes=header.get("n_nodes")).to_device()
            if key:
                # mglint: disable=MG006,MG007 — same _dispatch_lock contract as above: the LRU insert+evict runs under the dispatcher's lock
                self._graphs[key] = g
                while len(self._graphs) > self.MAX_CACHED_GRAPHS:  # mglint: disable=MG006 — under caller's _dispatch_lock
                    self._graphs.pop(next(iter(self._graphs)))  # mglint: disable=MG006,MG007 — under caller's _dispatch_lock
        ranks, err, iters = pr.pagerank(
            g, damping=header.get("damping", 0.85),
            max_iterations=header.get("max_iterations", 100),
            tol=header.get("tol", 1e-6))
        return ({"ok": True, "err": float(err), "iters": int(iters)},
                {"ranks": np.asarray(ranks, dtype=np.float32)})


# --------------------------------------------------------------------------
# client
# --------------------------------------------------------------------------

class KernelClient:
    def __init__(self, socket_path: str = DEFAULT_SOCKET,
                 timeout: float = 300.0) -> None:
        self.socket_path = socket_path
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.settimeout(timeout)
        self._sock.connect(socket_path)

    def call(self, header: dict, arrays=None):
        _send_msg(self._sock, header, arrays)
        return _recv_msg(self._sock)

    def ping(self) -> bool:
        try:
            h, _ = self.call({"op": "ping"})
            return bool(h.get("ok"))
        except (OSError, ConnectionError):
            return False

    def pagerank(self, src=None, dst=None, weights=None, n_nodes=None,
                 graph_key=None, **params):
        arrays = {}
        if src is not None:
            arrays["src"] = np.asarray(src, dtype=np.int64)
            arrays["dst"] = np.asarray(dst, dtype=np.int64)
            if weights is not None:
                arrays["weights"] = np.asarray(weights, dtype=np.float32)
        h, out = self.call({"op": "pagerank", "graph_key": graph_key,
                            "n_nodes": n_nodes, **params}, arrays)
        if not h.get("ok"):
            raise RuntimeError(h.get("error", "kernel server error"))
        return out["ranks"], h["err"], h["iters"]

    def shutdown(self) -> None:
        try:
            self.call({"op": "shutdown"})
        except (OSError, ConnectionError):
            pass

    def close(self) -> None:
        self._sock.close()


def ensure_server(socket_path: str = DEFAULT_SOCKET,
                  spawn_timeout_s: float = 120.0,
                  idle_timeout_s: float = 900.0):
    """Connect to the resident server, spawning it if absent.

    Returns a connected KernelClient, or None when the spawn TIMED OUT
    (the stillborn daemon is killed so it cannot keep competing for
    CPU). A daemon that DIED during init raises RuntimeError — that is
    a real regression, not an environmental condition, and callers'
    skip/fallback paths must not mask it."""
    try:
        c = KernelClient(socket_path, timeout=spawn_timeout_s)
        if c.ping():
            return c
        c.close()
    except OSError:
        pass
    proc = subprocess.Popen(
        [sys.executable, "-m", "memgraph_tpu.server.kernel_server",
         "--socket", socket_path, "--idle-timeout", str(idle_timeout_s)],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        start_new_session=True)   # survives the spawning client
    deadline = time.monotonic() + spawn_timeout_s
    while time.monotonic() < deadline:
        # keep polling the socket even if OUR child died: in a spawn
        # race the loser exits after probing a live responder (or on the
        # bind conflict) while the winner is still importing jax — its
        # server arrives soon
        try:
            c = KernelClient(socket_path, timeout=spawn_timeout_s)
            if c.ping():
                return c
            c.close()
        except OSError:
            time.sleep(0.1)
    if proc.poll() is not None:
        # nobody ever served AND our daemon died: a real init failure
        # (import error, crash), not environmental starvation
        raise RuntimeError(
            f"kernel server died during init (rc={proc.returncode})")
    try:
        proc.kill()               # a starved spawn must not linger
        proc.wait(timeout=10)
    except (OSError, subprocess.TimeoutExpired):
        pass
    return None


def main() -> None:
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--socket", default=DEFAULT_SOCKET)
    ap.add_argument("--idle-timeout", type=float, default=900.0)
    args = ap.parse_args()
    from ..utils.jax_cache import honor_jax_platforms_env
    honor_jax_platforms_env()
    KernelServer(args.socket, idle_timeout_s=args.idle_timeout).serve_forever()


if __name__ == "__main__":
    main()
