"""Resident kernel server: keeps one JAX/TPU runtime warm for
short-lived client processes — now a SUPERVISED service.

Measured on the tunneled axon platform (NOTES_ROUND4): every fresh
process pays ~1.5s to load the device executable stack before its first
kernel dispatch — which dominated CALL-to-first-record latency for CLI
tools and bench stages. The production server process (memgraph_tpu.main)
is naturally resident; this daemon gives every OTHER process the same
property: a unix-socket service holding the device runtime, compiled
kernels, and graph caches, so a cold client's first CALL costs one
socket round-trip plus device compute.

Resilience (r12) — device failure is a first-class, typed, recoverable
event end to end:

  * every dispatch returns a TYPED outcome: completed /
    deadline_exceeded / device_error / oom / shed / invalid. Clients
    raise matching exception types (AdmissionRejected, KernelOom, ...)
    so callers branch on class, not message text;
  * a per-request ``deadline_s`` bounds how long a client waits on the
    device — the dispatch runs on a worker thread, and a device hang
    yields a prompt ``deadline_exceeded`` instead of a wedged client;
  * an HBM ADMISSION GUARD estimates each request's device footprint
    against a budget and sheds (typed, counted, loudly logged) instead
    of letting one oversized request OOM the resident runtime for
    everyone;
  * compute routes through the RESUMABLE mesh entry points
    (parallel/analytics.py): long pagerank runs checkpoint every k
    iterations, so a mid-run device fault costs ≤ k redone iterations;
  * :class:`SupervisedKernelClient` is the client-side supervisor:
    idempotent requests retry under a shared RetryPolicy (per-attempt
    timeout + overall deadline), a health-check loop watches the
    daemon's ``health`` op, and a WEDGED (dispatch overdue) or LOST
    (device.lost killed the process) server is restarted;
  * everything is counted through observability.metrics — the server's
    own counters ride the ``health`` reply across the process boundary.

PPR serving plane (r16) — the first end-to-end query-serving path:
production graph traffic is per-user point queries, not whole-graph
sweeps, and N concurrent personalization vectors are ONE (n, B) SpMM
batch over the semiring core. The ``ppr`` op therefore does NOT dispatch
directly: requests enter a COALESCING QUEUE (:class:`PprServingPlane`)
and accumulate for a bounded window (time- or count-triggered,
``MEMGRAPH_TPU_PPR_BATCH_WINDOW_MS`` / ``_MAX_BATCH``), then execute as
one batched multi-source fixpoint — per-request top-k extracted on
device before the reply, typed per-request outcomes (one shed/oom/
deadline must never poison its batchmates), HBM admission accounting
for the whole batch footprint. A per-source RESULT CACHE keyed on
(graph version, source set, params) serves repeats without touching the
device; commits bump the storage change log, the server consumes the
deltas to invalidate only sources whose neighborhoods changed, and
invalidated vectors seed the next fixpoint (warm start — PPR is a
contraction, any seed converges). See docs/architecture.md §PPR
serving plane.

Protocol (local trusted unix socket): length-prefixed frames, each a
JSON header {op, arrays: [{name, dtype, shape}], ...params} followed by
the raw array bytes in order. Ops: ping, health, probe, pagerank,
ppr, shutdown.

Reference analog: none directly — the reference is a resident C++
daemon by construction (src/memgraph.cpp); this component restores that
property for out-of-process analytics callers.
"""

from __future__ import annotations

import json
import logging
import os
import signal
import socket
import struct
import subprocess
import sys
import threading
import time

import numpy as np

from ..observability import stats as mgstats
from ..observability import trace as mgtrace
from ..observability.metrics import global_metrics
from ..utils.devicefault import classify_device_error, device_fault_point
from ..utils.retry import RetryPolicy

log = logging.getLogger(__name__)

DEFAULT_SOCKET = os.environ.get(
    "MEMGRAPH_TPU_KERNEL_SERVER_SOCKET",
    os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), ".kernel_server.sock"))

#: typed per-dispatch outcomes (the taxonomy tests assert against)
DISPATCH_OUTCOMES = ("completed", "deadline_exceeded", "device_error",
                     "oom", "shed", "invalid")


def _resolve_hbm_budget() -> int:
    """Admission budget: env override, else 75% of the device's reported
    byte limit, else a conservative 4 GiB."""
    env = os.environ.get("MEMGRAPH_TPU_HBM_BUDGET_BYTES")
    if env:
        try:
            return int(env)
        except ValueError:
            log.warning("bad MEMGRAPH_TPU_HBM_BUDGET_BYTES=%r; ignoring",
                        env)
    try:
        import jax
        stats = jax.devices()[0].memory_stats() or {}
        limit = int(stats.get("bytes_limit") or 0)
        if limit > 0:
            return int(limit * 0.75)
    except Exception as e:  # noqa: BLE001 — backends without memory_stats
        log.debug("no device memory stats (%s); using default budget", e)
    return 4 << 30


def _resolve_checkpoint_every() -> int:
    try:
        return max(0, int(os.environ.get(
            "MEMGRAPH_TPU_CHECKPOINT_EVERY", "16")))
    except ValueError:
        return 16


# --------------------------------------------------------------------------
# admission estimators — machine-checked by `python -m tools.mgmem check`
# against XLA's buffer assignment for every manifest kernel
# --------------------------------------------------------------------------

def _pow2_bucket(n: int, minimum: int = 8) -> int:
    """Next power-of-two size class — mirrors ``ops.csr._bucket``, the
    padding the placed device arrays ACTUALLY get (tools/mgmem verifies
    the mirror stays exact)."""
    b = minimum
    while b < n:
        b <<= 1
    return b


def _padded_graph_dims(n_nodes: int, n_edges: int) -> tuple[int, int]:
    """(n_pad, e_pad) a ``from_coo`` device placement allocates for the
    declared counts. Estimates priced on RAW counts undercount by up to
    2x right past every bucket boundary — the compile pays for the
    bucket, not the request."""
    return (_pow2_bucket(int(n_nodes) + 1), _pow2_bucket(int(n_edges)))


#: per-algorithm device footprint coefficients over the PADDED dims:
#: ``node_bytes * n_pad + edge_bytes * e_pad`` bounds the compiled peak
#: (XLA argument + output + temp - alias bytes) of every manifest
#: kernel the algorithm can route to on the resident path (segment and
#: mesh backends; the streamed tier path is priced by
#: ``ops.tier.streamed_request_bytes``, and the MXU route is a
#: justified mgmem baseline exclusion). The values come from the
#: fitted footprint models and are enforced within [1x, 2x] of the
#: modeled peak by ``python -m tools.mgmem check`` — edit under that
#: gate, not by re-counting slots by hand.
_ALGO_FOOTPRINT = {
    "pagerank": (76, 36),
    "katz": (132, 24),
    "wcc": (132, 24),
    "labelprop": (68, 48),
    "bfs": (100, 20),
    "ppr": (28, 36),
}

#: unknown algorithms are priced at the column-wise max (shed-safe)
_ALGO_FOOTPRINT_DEFAULT = (max(n for n, _ in _ALGO_FOOTPRINT.values()),
                           max(e for _, e in _ALGO_FOOTPRINT.values()))


def _graph_footprint_bytes(algorithm, n_nodes: int, n_edges: int) -> int:
    """Modeled device peak of one resident fixpoint over the padded
    graph — the request estimate WITHOUT the wire-staging term. This is
    the cached-generation sizing path (r16): a graph_key-only request
    ships no bytes, but the fixpoint still pays the full padded-graph
    footprint."""
    node_b, edge_b = _ALGO_FOOTPRINT.get(str(algorithm),
                                         _ALGO_FOOTPRINT_DEFAULT)
    n_pad, e_pad = _padded_graph_dims(n_nodes, n_edges)
    return n_pad * node_b + e_pad * edge_b


def _estimate_request_bytes(header: dict, arrays: dict) -> int:
    """Request HBM footprint estimate: the padded-graph fixpoint peak
    (per-algorithm coefficients from XLA's buffer assignment) plus one
    copy of the wire arrays — the H2D staging form that briefly
    coexists with the placed graph."""
    wire_bytes = sum(int(np.prod(a.shape, dtype=np.int64))
                     * a.dtype.itemsize for a in arrays.values())
    n_nodes = int(header.get("n_nodes") or 0)
    src = arrays.get("src")
    n_edges = int(src.shape[0]) if src is not None \
        else int(header.get("n_edges") or 0)
    return wire_bytes + _graph_footprint_bytes(
        header.get("algorithm", "pagerank"), n_nodes, n_edges)


def _generation_modeled_bytes(gen) -> int:
    """Modeled device peak of one RESIDENT generation, priced at the
    column-wise worst case across algorithms: the daemon cannot know
    which fixpoint the next request will run over a cached graph, so
    the capacity gauge must be shed-safe (an overestimate wastes
    headroom; an underestimate lies to the planner)."""
    return _graph_footprint_bytes("*", gen.n_nodes, gen.n_edges)


#: f32 slots of per-lane, per-node iteration state the batched PPR
#: fixpoint keeps live (x, new, acc, personalization + err scratch)
_PPR_LANE_NODE_SLOTS = 6

#: bytes per PADDED edge PER LANE: the batched SpMM gather materializes
#: each edge's contribution once per personalization column
_PPR_LANE_EDGE_BYTES = 6

#: compile-time lane buckets — mirrors ops.pagerank._PPR_LANE_BUCKETS
#: (tools/mgmem verifies the mirror stays exact)
_PPR_LANE_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128)


def _lane_state_bytes(n_nodes: int, n_edges: int,
                      n_lanes: int = 1) -> int:
    """Device bytes the batched PPR fixpoint pays for its lanes, priced
    at the POWER-OF-TWO BUCKET the compile actually allocates: 33
    requested lanes build the 64-wide kernel, and every lane column
    carries O(n) state plus a per-edge gather slice."""
    lanes = next((b for b in _PPR_LANE_BUCKETS
                  if b >= max(1, int(n_lanes))), _PPR_LANE_BUCKETS[-1])
    n_pad, e_pad = _padded_graph_dims(n_nodes, n_edges)
    return lanes * (n_pad * 4 * _PPR_LANE_NODE_SLOTS
                    + e_pad * _PPR_LANE_EDGE_BYTES)


def _ppr_chunk_lanes(n_nodes: int, n_edges: int, budget: int) -> int:
    """Widest lane bucket whose priced batch (graph footprint +
    bucketed lane state) fits the budget — the chunk size the batch
    drain admits. Falls back to single-lane chunks past the budget;
    submit-side admission already bounded that case."""
    graph = _graph_footprint_bytes("ppr", n_nodes, n_edges)
    for b in reversed(_PPR_LANE_BUCKETS):
        if graph + _lane_state_bytes(n_nodes, n_edges, b) <= budget:
            return b
    return 1


def _tier_precision(precision) -> str:
    """Block-compression precision for a streamed run: the request's
    precision when the tier codec supports it, f32 otherwise."""
    p = str(precision)
    return p if p in ("f32", "bf16", "int8") else "f32"


def probe_device():
    """Tiny end-to-end device check: a compiled matmul with a host
    transfer forcing completion. Shared by the server warm-up, the
    ``probe`` op, and bench.py's probe stage — and guarded by the
    device fault point so probe failures are injectable too.
    Returns (checksum, platform)."""
    device_fault_point()
    import jax
    import jax.numpy as jnp
    x = jnp.ones((128, 128), jnp.float32)
    return float((x @ x).sum()), jax.devices()[0].platform


# --------------------------------------------------------------------------
# typed client errors (one per server outcome)
# --------------------------------------------------------------------------


class KernelServerError(RuntimeError):
    """Base kernel-server failure; carries the typed outcome."""

    def __init__(self, message: str, outcome: str = "invalid",
                 retryable: bool = False) -> None:
        super().__init__(message)
        self.outcome = outcome
        self.retryable = retryable


class AdmissionRejected(KernelServerError):
    """The HBM admission guard shed this request (outcome "shed").
    Deliberately NOT retryable: the same request against the same budget
    sheds again — resize the request or raise the budget."""

    def __init__(self, message: str) -> None:
        super().__init__(message, outcome="shed", retryable=False)


class KernelOom(KernelServerError):
    """Device memory exhausted during dispatch (outcome "oom")."""

    def __init__(self, message: str) -> None:
        super().__init__(message, outcome="oom", retryable=False)


class KernelDeviceError(KernelServerError):
    """Device-side dispatch failure (outcome "device_error"); the op is
    pure, so idempotent retry is safe."""

    def __init__(self, message: str) -> None:
        super().__init__(message, outcome="device_error", retryable=True)


class KernelDeadlineExceeded(KernelServerError):
    """The dispatch missed its deadline (outcome "deadline_exceeded") —
    possibly a wedged device; the supervisor health-checks on this."""

    def __init__(self, message: str) -> None:
        super().__init__(message, outcome="deadline_exceeded",
                         retryable=True)


_OUTCOME_ERRORS = {
    "shed": AdmissionRejected,
    "oom": KernelOom,
    "device_error": KernelDeviceError,
    "deadline_exceeded": KernelDeadlineExceeded,
}


def _raise_for_reply(header: dict):
    outcome = header.get("outcome", "invalid")
    cls = _OUTCOME_ERRORS.get(outcome)
    msg = header.get("error", "kernel server error")
    if cls is not None:
        raise cls(msg)
    raise KernelServerError(msg, outcome=outcome,
                            retryable=bool(header.get("retryable")))


# --------------------------------------------------------------------------
# framing
# --------------------------------------------------------------------------

def _send_msg(sock: socket.socket, header: dict,
              arrays: dict[str, np.ndarray] | None = None) -> None:
    arrays = arrays or {}
    header = dict(header)
    header["arrays"] = [
        {"name": k, "dtype": str(v.dtype), "shape": list(v.shape)}
        for k, v in arrays.items()]
    hb = json.dumps(header).encode("utf-8")
    parts = [struct.pack("<I", len(hb)), hb]
    for v in arrays.values():
        parts.append(np.ascontiguousarray(v).tobytes())
    sock.sendall(b"".join(parts))


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return bytes(buf)


def _recv_msg(sock: socket.socket):
    (hlen,) = struct.unpack("<I", _recv_exact(sock, 4))
    header = json.loads(_recv_exact(sock, hlen))
    arrays = {}
    for spec in header.pop("arrays", []):
        dt = np.dtype(spec["dtype"])
        count = int(np.prod(spec["shape"], dtype=np.int64)) if spec["shape"] \
            else 1
        raw = _recv_exact(sock, count * dt.itemsize)
        arrays[spec["name"]] = np.frombuffer(raw, dtype=dt).reshape(
            spec["shape"])
    return header, arrays


# --------------------------------------------------------------------------
# PPR serving plane: result cache + coalescing queue
# --------------------------------------------------------------------------


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


#: above this neighborhood size an entry records None — "invalidate on
#: any change" — instead of an exact set (hub sources touch everything)
PPR_NEIGH_CAP = 4096


def _source_neighborhood(graph, sources, cap: int = PPR_NEIGH_CAP):
    """Dense indices whose mutation must invalidate a cached PPR vector
    restarted on ``sources``: the sources plus their out-neighbors (the
    rows the restart mass crosses first). None = unbounded (treat every
    change as relevant)."""
    if graph.host_coo is None:
        return None
    src, dst, _w = graph.host_coo
    sel = np.isin(np.asarray(src), np.asarray(sources))
    neigh = set(int(i) for i in np.asarray(dst)[sel])
    neigh.update(int(s) for s in np.asarray(sources))
    if len(neigh) > cap:
        return None
    return frozenset(neigh)


class _PprCacheEntry:
    """One cached PPR vector. ``fresh`` entries serve directly; STALE
    entries (their source neighborhood changed) are never served but
    seed the recomputation's fixpoint (warm start)."""

    __slots__ = ("version", "ranks", "err", "iters", "neigh", "fresh")

    def __init__(self, version, ranks, err, iters, neigh) -> None:
        self.version = version
        self.ranks = ranks              # np (n_nodes,) float32
        self.err = err
        self.iters = iters
        self.neigh = neigh              # frozenset | None (= any change)
        self.fresh = True


class PprResultCache:
    """Per-source PPR result cache with change-log-driven invalidation.

    Keyed on (graph_key, source set, damping, tol, precision); bounded
    LRU. The consumer-side route layer ships each commit's change-log
    delta (dense indices) with the next request; :meth:`note_version`
    applies it: entries whose source neighborhood intersects the delta
    are DEMOTED to warm-start seeds, everything else is promoted to the
    new version — a stale read across a version bump is impossible, and
    untouched sources keep their hits. An unknowable delta (log
    evicted, node set changed) invalidates the whole graph_key.
    """

    def __init__(self, capacity: int | None = None) -> None:
        from collections import OrderedDict
        from ..utils.locks import tracked_lock
        from ..utils.sanitize import shared_field
        self.capacity = capacity if capacity is not None \
            else _env_int("MEMGRAPH_TPU_PPR_CACHE_ENTRIES", 512)
        self._lock = tracked_lock("PprResultCache._lock")
        self._entries: "OrderedDict[tuple, _PprCacheEntry]" = OrderedDict()
        self._known: dict[str, int] = {}    # graph_key -> newest version
        shared_field(self, "_entries", "_known")

    @staticmethod
    def key(graph_key, sources, damping, tol, precision) -> tuple:
        return (graph_key, tuple(int(s) for s in sources),
                float(damping), float(tol), str(precision))

    def known_version(self, graph_key) -> int | None:
        from ..utils.sanitize import shared_read
        with self._lock:
            shared_read(self, "_known")
            return self._known.get(graph_key)

    def note_version(self, graph_key, version: int, base_version,
                     changed, ids_stable: bool) -> None:
        """Advance a graph_key to ``version``. ``changed`` is the dense
        index delta covering (base_version, version] or None when
        unknowable; ``ids_stable`` says the dense-id layout survived."""
        from ..utils.sanitize import shared_write
        if graph_key is None:
            return
        with self._lock:
            shared_write(self, "_known")
            known = self._known.get(graph_key)
            if known is None or version <= known:
                self._known.setdefault(graph_key, version)
                return
            targeted = (ids_stable and base_version == known
                        and changed is not None)
            changed_set = frozenset(int(i) for i in changed) \
                if targeted else None
            for key, entry in list(self._entries.items()):
                if key[0] != graph_key:
                    continue
                if targeted:
                    if entry.neigh is not None and \
                            not (entry.neigh & changed_set):
                        entry.version = version      # provably untouched
                        continue
                    entry.fresh = False              # warm-start seed
                    global_metrics.increment("ppr.cache_invalidate_total")
                elif ids_stable:
                    entry.fresh = False
                    global_metrics.increment("ppr.cache_invalidate_total")
                else:
                    # dense-id layout changed: the vector indexes the
                    # wrong nodes — useless even as a seed
                    del self._entries[key]
                    global_metrics.increment("ppr.cache_invalidate_total")
            self._known[graph_key] = version

    def lookup(self, key: tuple):
        """("hit", entry) | ("warm", entry) | ("miss", None)."""
        from ..utils.sanitize import shared_read
        with self._lock:
            shared_read(self, "_entries")
            entry = self._entries.get(key)
            if entry is None:
                return "miss", None
            if entry.fresh and entry.version == self._known.get(key[0]):
                self._entries.move_to_end(key)
                return "hit", entry
            return "warm", entry

    def insert(self, key: tuple, entry: _PprCacheEntry) -> None:
        from ..utils.sanitize import shared_write
        with self._lock:
            shared_write(self, "_entries")
            known = self._known.get(key[0])
            if known is not None and entry.version < known:
                return          # a newer delta landed mid-compute
            self._entries[key] = entry
            self._entries.move_to_end(key)
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)


class _PprPending:
    """One queued PPR request awaiting its batch."""

    __slots__ = ("header", "arrays", "carrier", "event", "reply",
                 "out_arrays", "warm_entry", "abandoned", "t_enqueued")

    def __init__(self, header, arrays, carrier, warm_entry) -> None:
        self.header = header
        self.arrays = arrays
        self.carrier = carrier
        self.event = threading.Event()
        self.reply = None
        self.out_arrays = None
        self.warm_entry = warm_entry
        self.abandoned = False
        self.t_enqueued = time.monotonic()


def _topk_host(vec: np.ndarray, k: int):
    """Host-side top-k for cache hits (no device round trip)."""
    k = max(1, min(int(k), len(vec)))
    idx = np.argpartition(-vec, k - 1)[:k]
    idx = idx[np.argsort(-vec[idx], kind="stable")]
    return vec[idx].astype(np.float32), idx.astype(np.int32)


class PprServingPlane:
    """Request-coalescing batched PPR with result caching.

    Concurrent ``ppr`` requests accumulate for a bounded window —
    time-triggered (MEMGRAPH_TPU_PPR_BATCH_WINDOW_MS, default 4ms) or
    count-triggered (MEMGRAPH_TPU_PPR_MAX_BATCH, default 32) — then
    execute as ONE batched multi-source SpMM fixpoint per parameter
    group (requests with differing damping/tol/precision NEVER share a
    fixpoint). Each member gets a TYPED outcome; admission accounts the
    whole batch footprint and splits oversized groups into sub-batches
    instead of shedding riders.
    """

    def __init__(self, server: "KernelServer") -> None:
        import queue as _queue
        from ..utils.locks import tracked_lock
        self.server = server
        self.window_s = _env_float(
            "MEMGRAPH_TPU_PPR_BATCH_WINDOW_MS", 4.0) / 1e3
        self.max_batch = max(1, _env_int("MEMGRAPH_TPU_PPR_MAX_BATCH", 32))
        self.max_queue = max(1, _env_int("MEMGRAPH_TPU_PPR_MAX_QUEUE", 256))
        self.cache = PprResultCache()
        self._queue: "_queue.Queue[_PprPending]" = _queue.Queue()
        self._thread = None
        self._thread_lock = tracked_lock("PprServingPlane._thread_lock")
        self._graph_versions: dict = {}   # batcher-thread only

    # --- request side (connection threads) ---------------------------------

    def submit(self, header: dict, arrays: dict):
        """Blocking request entry: cache probe → admission → coalescing
        queue → (reply, out_arrays). Runs on the connection thread."""
        global_metrics.increment("ppr.requests_total")
        sources = arrays.get("sources")
        if sources is None or len(sources) == 0:
            return ({"ok": False, "outcome": "invalid",
                     "error": "ppr request carries no sources"}, None)
        carrier = header.pop("trace", None)
        graph_key = header.get("graph_key")
        version = int(header.get("graph_version") or 0)
        self.cache.note_version(
            graph_key, version, header.get("base_version"),
            arrays.get("changed") if header.get("has_delta") else None,
            bool(header.get("ids_stable", True)))
        ckey = self.cache.key(graph_key, sources,
                              header.get("damping", 0.85),
                              header.get("tol", 1e-6),
                              header.get("precision", "f32"))
        warm_entry = None
        if graph_key is not None:
            t0 = time.perf_counter()
            t_wall = time.time()
            status, entry = self.cache.lookup(ckey)
            if status == "hit":
                global_metrics.increment("ppr.cache_hit_total")
                return self._reply_from_vector(
                    header, entry.ranks, entry.err, entry.iters,
                    cache="hit", batch_size=1, coalesced=False,
                    carrier=carrier, t_wall=t_wall,
                    dur=time.perf_counter() - t0)
            if status == "warm":
                warm_entry = entry
            global_metrics.increment("ppr.cache_miss_total")

        n_nodes = int(header.get("n_nodes") or 0)
        src = arrays.get("src")
        n_edges = int(src.shape[0]) if src is not None else 0
        if src is None and graph_key is not None:
            # cached-generation sizing (r16): a graph_key-only request
            # ships no edges, so the wire-driven estimate misses the
            # real footprint — size admission off the resident
            # generation's CURRENT counts (same benign unlocked peek as
            # the supervised path)
            gen = self.server._graphs.get(graph_key)  # mglint: disable=MG006 — benign unlocked estimate read; admission must not queue behind a dispatch holding _dispatch_lock
            if gen is not None:
                n_nodes = n_nodes or gen._n_nodes
                n_edges = int(np.asarray(gen._coo[0]).shape[0])
        est = _estimate_request_bytes(
            {**header, "algorithm": "ppr", "n_nodes": n_nodes,
             "n_edges": n_edges}, arrays) \
            + _lane_state_bytes(n_nodes, n_edges, 1)
        if est > self.server.hbm_budget_bytes:
            return self._shed(
                f"estimated footprint {est} bytes exceeds HBM budget "
                f"{self.server.hbm_budget_bytes} bytes")
        depth = self._queue.qsize()
        if depth >= self.max_queue:
            # backpressure: the saturation plane flips /health to 503
            # before this point; past it we shed typed instead of
            # letting the queue (and every rider's latency) grow
            return self._shed(
                f"PPR coalescing queue saturated ({depth} >= "
                f"{self.max_queue} pending)")
        pending = _PprPending(header, arrays, carrier, warm_entry)
        self._ensure_thread()
        self._queue.put(pending)
        global_metrics.set_gauge("ppr.queue_depth",
                                 float(self._queue.qsize()))
        deadline_s = header.get("deadline_s")
        wait_s = float(deadline_s) if deadline_s \
            else self.server.wedge_after_s + 30.0
        if not pending.event.wait(wait_s):
            pending.abandoned = True
            self.server._count("deadline_exceeded")
            log.warning("ppr: request exceeded its %.3fs deadline in "
                        "the coalescing plane", wait_s)
            return ({"ok": False, "outcome": "deadline_exceeded",
                     "retryable": True,
                     "error": f"ppr request exceeded {wait_s}s "
                              "deadline"}, None)
        return pending.reply, pending.out_arrays

    def _shed(self, why: str):
        self.server._count("shed")
        global_metrics.increment("ppr.shed_total")
        global_metrics.increment("kernel_server.admission_rejected_total")
        log.warning("ppr: SHED request — %s", why)
        return ({"ok": False, "outcome": "shed", "retryable": False,
                 "error": f"AdmissionRejected: {why}"}, None)

    def _reply_from_vector(self, header, ranks, err, iters, *, cache,
                           batch_size, coalesced, stages=None,
                           carrier=None, t_wall=None, dur=None,
                           topk=None):
        k = int(header.get("top_k") or 0)
        reply = {"ok": True, "outcome": "completed", "err": float(err),
                 "iters": int(iters), "cache": cache,
                 "batch_size": int(batch_size),
                 "coalesced": bool(coalesced)}
        if stages:
            reply["stages"] = stages
        if carrier and carrier.get("trace_id"):
            with mgtrace.adopt(carrier):
                mgtrace.record_span(
                    "kernel.dispatch", t_wall or time.time(), dur or 0.0,
                    op="ppr", batch=int(batch_size), cache=cache)
            spans = mgtrace.take_trace(carrier["trace_id"])
            if spans:
                reply["trace_spans"] = spans
        global_metrics.observe("kernel_server.dispatch_latency_sec",
                               dur if dur is not None else 0.0,
                               trace_id=(carrier or {}).get("trace_id"))
        if k > 0:
            if topk is not None:
                vals, idx = topk
                vals, idx = vals[:k], idx[:k]
            else:
                vals, idx = _topk_host(np.asarray(ranks), k)
            return reply, {"topk_val": np.asarray(vals, dtype=np.float32),
                           "topk_idx": np.asarray(idx, dtype=np.int32)}
        return reply, {"ranks": np.asarray(ranks, dtype=np.float32)}

    # --- batch side (the one batcher thread) -------------------------------

    def _ensure_thread(self) -> None:
        import threading
        with self._thread_lock:
            if self._thread is not None and self._thread.is_alive():
                return
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="ks-ppr-batcher")
            self._thread.start()

    def _run(self) -> None:
        import queue as _queue
        while not self.server._shutdown.is_set():
            try:
                first = self._queue.get(timeout=0.25)
            except _queue.Empty:
                continue
            batch = [first]
            deadline = time.monotonic() + self.window_s
            while len(batch) < self.max_batch:
                rem = deadline - time.monotonic()
                if rem <= 0:
                    break
                try:
                    batch.append(self._queue.get(
                        timeout=max(rem, 0.0005)))
                except _queue.Empty:
                    break
            global_metrics.set_gauge("ppr.queue_depth",
                                     float(self._queue.qsize()))
            global_metrics.set_gauge("ppr.window_occupancy",
                                     len(batch) / self.max_batch)
            groups: dict = {}
            for m in batch:
                h = m.header
                gk = (h.get("graph_key"), float(h.get("damping", 0.85)),
                      float(h.get("tol", 1e-6)),
                      int(h.get("max_iterations", 100)),
                      str(h.get("precision", "f32")))
                groups.setdefault(gk, []).append(m)
            for members in groups.values():
                try:
                    self._execute_group(members)
                except Exception:   # noqa: BLE001 — serving must survive
                    log.exception("ppr: group execution failed "
                                  "unexpectedly")
                    self._fail_group(members, "invalid", False,
                                     "internal ppr batch failure")
        # drain: pending requests must not leave connection threads
        # blocked across shutdown
        while True:
            try:
                m = self._queue.get_nowait()
            except _queue.Empty:
                break
            self._fail_group([m], "invalid", False,
                             "kernel server shutting down")

    def _fail_group(self, members, outcome, retryable, error) -> None:
        """Typed failure for EVERY live member — a batch dies whole or
        answers whole, never half (device_chaos contract)."""
        for m in members:
            if m.reply is not None:
                continue
            self.server._count(outcome)
            m.reply = {"ok": False, "outcome": outcome,
                       "retryable": retryable, "error": error}
            m.event.set()

    def _resolve_group_graph(self, members):
        """Resolve (importing/refreshing if needed) the group's graph.
        Runs under _dispatch_lock on the batcher thread.

        Rides the resident-generation layer (r19 mgdelta): the carrier
        member is whichever request can ADVANCE the resident graph —
        full edge arrays, or the change-log delta payload (``changed``
        + the changed vertices' current incident edges), which
        refreshes the resident snapshot O(delta) instead of
        re-importing the full edge list. The cache demotion path
        (note_version) and this refresh consume the SAME shipped delta,
        so a commit costs one O(delta) splice, not a re-import plus a
        private neighborhood walk."""
        key = members[0].header.get("graph_key")
        carrier_m = None

        def _version(m):
            return int(m.header.get("graph_version") or 0)

        for m in members:
            if ("src" in m.arrays or ("changed" in m.arrays
                                      and "inc_src" in m.arrays)) \
                    and (carrier_m is None
                         or _version(m) > _version(carrier_m)):
                carrier_m = m
        m = carrier_m or members[0]
        gen = self.server._resolve_generation(m.header, m.arrays)
        if gen is None:
            return None
        if key is not None:
            self._graph_versions[key] = max(
                gen.version, self._graph_versions.get(key) or 0)
        return gen.graph

    def _execute_group(self, members) -> None:
        """One parameter group → one batched fixpoint dispatch."""
        from ..observability import stats as mgstats
        server = self.server
        did = server._dispatch_begin(server.wedge_after_s)
        global_metrics.increment("ppr.batches_total")
        global_metrics.observe("ppr.batch_size", float(len(members)))
        if len(members) > 1:
            global_metrics.increment("ppr.coalesced_total",
                                     delta=len(members))
        t0 = time.perf_counter()
        t_wall = time.time()
        acc = mgstats.StageAccumulator()
        results = None
        live = []
        try:
            try:
                with mgstats.collecting_stages(acc):
                    with server._dispatch_lock:
                        device_fault_point()
                        g = self._resolve_group_graph(members)
                        if g is None:
                            self._fail_group(
                                members, "invalid", False,
                                "unknown graph_key and no edge arrays "
                                "supplied")
                            return
                        live, results = self._compute(g, members)
            except BaseException as e:  # noqa: BLE001 — classified below
                kind = classify_device_error(e)
                if kind == "oom":
                    outcome, retryable = "oom", False
                elif kind in ("device_error", "device_lost"):
                    outcome, retryable = "device_error", True
                else:
                    outcome, retryable = "invalid", False
                log.warning("ppr: batch of %d failed [%s]: %s",
                            len(members), outcome, e)
                self._fail_group(members, outcome, retryable,
                                 f"{type(e).__name__}: {e}")
                return
            dur = time.perf_counter() - t0
            # pro-rata device-stage attribution: the batch's HBM-seconds
            # split evenly across its riders, so per-query PROFILE sums
            # stay truthful instead of charging the whole batch to one
            snap = acc.snapshot()
            share = 1.0 / max(1, len(live))
            stages = {name: {"seconds": slot["seconds"] * share,
                             "count": slot["count"]}
                      for name, slot in snap.items()} if snap else None
            for m, res in zip(live, results):
                ranks, err, iters, cache_state, topk = res
                m.reply, m.out_arrays = self._reply_from_vector(
                    m.header, ranks, err, iters, cache=cache_state,
                    batch_size=len(members),
                    coalesced=len(members) > 1, stages=stages,
                    carrier=m.carrier, t_wall=t_wall, dur=dur,
                    topk=topk)
                server._count("completed")
                m.event.set()
        finally:
            server._dispatch_end(did)

    def _compute(self, g, members):
        """Batched fixpoint over the group's live members (under
        _dispatch_lock). Returns (live_members, results) where results
        align with live_members as (ranks, err, iters, cache_state).
        Invalid members are replied typed HERE — they must not poison
        the batch."""
        from ..ops.pagerank import personalized_pagerank_batch, ppr_topk
        h0 = members[0].header
        damping = float(h0.get("damping", 0.85))
        tol = float(h0.get("tol", 1e-6))
        max_iterations = int(h0.get("max_iterations", 100))
        precision = str(h0.get("precision", "f32"))
        graph_key = h0.get("graph_key")
        version = self._graph_versions.get(graph_key, 0)

        live = []
        for m in members:
            sources = np.asarray(m.arrays["sources"], dtype=np.int32)
            if sources.size == 0 or sources.min() < 0 \
                    or sources.max() >= g.n_nodes:
                self.server._count("invalid")
                m.reply = {"ok": False, "outcome": "invalid",
                           "retryable": False,
                           "error": f"sources out of range for graph "
                                    f"with {g.n_nodes} nodes"}
                m.event.set()
                continue
            live.append(m)
        if not live:
            return [], []

        # admission: chunk the batch at the widest LANE BUCKET whose
        # priced footprint (graph + bucketed lane state) fits the HBM
        # budget. The compile allocates the power-of-two bucket, so
        # pricing requested lanes would undercount right past every
        # bucket boundary (33 live members -> the 64-wide kernel)
        max_lanes = _ppr_chunk_lanes(g.n_nodes, g.n_edges,
                                     self.server.hbm_budget_bytes)

        results = []
        for lo in range(0, len(live), max_lanes):
            chunk = live[lo:lo + max_lanes]
            source_sets = [np.asarray(m.arrays["sources"],
                                      dtype=np.int32) for m in chunk]
            x0 = None
            warm_lanes = []
            if any(m.warm_entry is not None
                   and len(m.warm_entry.ranks) == g.n_nodes
                   for m in chunk):
                x0 = np.zeros((g.n_pad, len(chunk)), dtype=np.float32)
                for lane, m in enumerate(chunk):
                    e = m.warm_entry
                    if e is not None and len(e.ranks) == g.n_nodes:
                        x0[:g.n_nodes, lane] = e.ranks
                        warm_lanes.append(lane)
                        global_metrics.increment("ppr.warm_start_total")
                    else:
                        s = source_sets[lane]
                        x0[s, lane] = np.float32(1.0) \
                            / np.float32(len(s))
            x_dev, err_dev, iter_dev = personalized_pagerank_batch(
                g, source_sets, damping=damping,
                max_iterations=max_iterations, tol=tol,
                precision=precision, x0=x0, raw=True)
            # per-request top-k extracted ON DEVICE (one jitted top_k
            # over the whole batch) before the O(n) host transfer the
            # cache fill pays anyway
            k_max = max((int(m.header.get("top_k") or 0) for m in chunk),
                        default=0)
            tvals = tidx = None
            device_out = [x_dev, err_dev, iter_dev]
            if k_max > 0:
                device_out += list(ppr_topk(x_dev.T[:len(chunk)],
                                            g.n_nodes, k_max, raw=True))
            # THE one fused host sync per chunk: every device output of
            # the batch (iterate, per-lane err/iters, top-k) crosses in
            # a single device_get instead of one transfer per epilogue
            import jax
            host = jax.device_get(device_out)  # mglint: disable=MG009 — replies must ship host bytes; this IS the single fused result transfer the drain loop pays per chunk
            x_host, errs, iters = host[0], host[1], host[2]
            if k_max > 0:
                tvals, tidx = host[3], host[4]
            ranks = x_host[:g.n_nodes, :len(chunk)].T
            warm_set = set(warm_lanes)
            for lane, m in enumerate(chunk):
                vec = np.ascontiguousarray(ranks[lane])
                if graph_key is not None:
                    ckey = self.cache.key(
                        graph_key, m.arrays["sources"], damping, tol,
                        precision)
                    self.cache.insert(ckey, _PprCacheEntry(
                        version, vec, float(errs[lane]),
                        int(iters[lane]),
                        _source_neighborhood(g, m.arrays["sources"])))
                topk = (tvals[lane], tidx[lane]) \
                    if tvals is not None else None
                results.append((vec, float(errs[lane]),
                                int(iters[lane]),
                                "warm" if lane in warm_set else "miss",
                                topk))
        return live, results


# --------------------------------------------------------------------------
# server
# --------------------------------------------------------------------------

class KernelServer:
    """One thread per connection; device dispatch serialized by a lock
    (one chip — concurrent kernels would just queue anyway). Every
    dispatch runs on a worker thread under a per-request deadline: a
    wedged device costs the caller a typed ``deadline_exceeded``, never
    a silent hang, and the ``health`` op exposes the overdue dispatch so
    the client-side supervisor can restart the process."""

    def __init__(self, socket_path: str = DEFAULT_SOCKET,
                 idle_timeout_s: float = 0.0,
                 hbm_budget_bytes: int | None = None,
                 checkpoint_every: int | None = None,
                 wedge_after_s: float | None = None) -> None:
        import threading
        self.socket_path = socket_path
        self.idle_timeout_s = idle_timeout_s
        self.hbm_budget_bytes = hbm_budget_bytes \
            if hbm_budget_bytes is not None else _resolve_hbm_budget()
        self.checkpoint_every = checkpoint_every \
            if checkpoint_every is not None else _resolve_checkpoint_every()
        self.wedge_after_s = wedge_after_s if wedge_after_s is not None \
            else float(os.environ.get(
                "MEMGRAPH_TPU_KS_WEDGE_AFTER_S", "60"))
        self._graphs: dict = {}      # graph_key -> delta.ResidentGraph
        from ..utils.locks import tracked_lock
        from ..utils.sanitize import shared_field
        self._dispatch_lock = tracked_lock("KernelServer._dispatch_lock")
        self._shutdown = threading.Event()
        # written by every connection thread, read by the accept loop's
        # idle-timeout check — a leaf lock, never held across dispatch
        self._activity_lock = tracked_lock("KernelServer._activity_lock")
        self._last_activity = time.monotonic()
        # dispatch bookkeeping for the health op — a leaf lock too: the
        # health reply must never wait behind a wedged dispatch
        self._stats_lock = tracked_lock("KernelServer._stats_lock")
        self._active: dict[int, tuple[float, float | None]] = {}
        self._dispatch_seq = 0
        self._graphs_cached = 0
        self._modeled_peaks: dict = {}  # graph_key -> modeled peak bytes
        self._started = time.monotonic()
        self._platform = "unknown"
        self._sock_ino = None        # inode of OUR bound socket path
        shared_field(self, "_graphs", "_last_activity", "_active",
                     "_dispatch_seq", "_graphs_cached", "_platform",
                     "_modeled_peaks")
        # saturation plane: the admission budget is a bounded resource —
        # export it so capacity planning can see utilization vs limit
        global_metrics.set_gauge("kernel_server.hbm_budget_bytes",
                                 float(self.hbm_budget_bytes))
        global_metrics.set_gauge("kernel_server.hbm_modeled_peak_bytes",
                                 0.0)
        # PPR serving plane: coalescing queue + result cache (r16)
        self._ppr = PprServingPlane(self)

    def _touch_activity(self) -> None:
        from ..utils.sanitize import shared_write
        with self._activity_lock:
            shared_write(self, "_last_activity")
            self._last_activity = time.monotonic()

    def _idle_for(self) -> float:
        from ..utils.sanitize import shared_read
        with self._activity_lock:
            shared_read(self, "_last_activity")
            return time.monotonic() - self._last_activity

    def _warm(self) -> None:
        """Touch the device so the first client request pays no init."""
        from ..utils.sanitize import shared_write
        _, platform = probe_device()
        with self._stats_lock:
            shared_write(self, "_platform")
            self._platform = platform

    def serve_forever(self) -> None:
        import errno
        import threading

        # Spawn-race discipline (ADVICE r5): never unlink-before-bind.
        # A live responder on the path means another daemon already won —
        # exit and let clients use it. Only a provably-stale path (connect
        # refused) is unlinked, and shutdown unlinks only while the inode
        # still belongs to THIS server, so a losing daemon's exit can
        # never orphan the winner's socket.
        try:
            probe = KernelClient(self.socket_path, timeout=5.0)
            alive = probe.ping()
            probe.close()
            if alive:
                return           # already running; we lost the race
        except OSError:
            pass                 # nothing listening (or no socket yet)
        srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        try:
            srv.bind(self.socket_path)
        except OSError as e:
            if e.errno != errno.EADDRINUSE:
                raise
            # path exists but nobody answered the probe: stale socket
            # from a crashed daemon — reclaim it
            try:
                os.unlink(self.socket_path)
            except OSError:
                pass
            srv.bind(self.socket_path)
        try:
            self._sock_ino = os.stat(self.socket_path).st_ino
        except OSError:
            self._sock_ino = None
        # serving-plane backlog: the PPR coalescer exists precisely for
        # bursts of concurrent clients, so simultaneous connects must
        # not bounce off a tiny accept queue
        srv.listen(128)
        self._warm()
        self._touch_activity()
        srv.settimeout(1.0)
        while not self._shutdown.is_set():
            try:
                conn, _ = srv.accept()
            except socket.timeout:
                if self.idle_timeout_s and \
                        self._idle_for() > self.idle_timeout_s:
                    break
                continue
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()
        srv.close()
        try:
            if self._sock_ino is not None and \
                    os.stat(self.socket_path).st_ino == self._sock_ino:
                os.unlink(self.socket_path)
        except OSError:
            pass

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            while not self._shutdown.is_set():
                try:
                    header, arrays = _recv_msg(conn)
                except (ConnectionError, struct.error, OSError,
                        ValueError):
                    # ValueError: garbage JSON header / bad dtype from
                    # a confused client — drop the connection, not the
                    # serving thread
                    return
                self._touch_activity()
                op = header.get("op")
                try:
                    if op == "ping":
                        _send_msg(conn, {"ok": True, "pid": os.getpid()})
                    elif op == "health":
                        _send_msg(conn, self._health_reply())
                    elif op == "shutdown":
                        _send_msg(conn, {"ok": True})
                        self._shutdown.set()
                        return
                    elif op == "ppr":
                        # the coalescing plane: this connection thread
                        # blocks while its request rides a batch; the
                        # batcher thread owns the device dispatch
                        reply, out_arrays = self._ppr.submit(header,
                                                             arrays)
                        _send_msg(conn, reply, out_arrays)
                    elif op in ("pagerank", "semiring", "probe", "lane"):
                        # supervised: admission guard + worker thread +
                        # per-request deadline; the reply ships AFTER
                        # the dispatch lock is released — a slow client
                        # must not hold up other clients' dispatches
                        reply, out_arrays = self._supervised(op, header,
                                                             arrays)
                        _send_msg(conn, reply, out_arrays)
                    else:
                        _send_msg(conn, {"ok": False, "outcome": "invalid",
                                         "error": f"unknown op {op!r}"})
                except KernelServerError as e:
                    # typed dispatch failures keep their outcome on the
                    # wire so clients rehydrate the taxonomy instead of
                    # a generic "invalid"
                    try:
                        _send_msg(conn, {"ok": False,
                                         "outcome": e.outcome,
                                         "retryable": e.retryable,
                                         "error": str(e)})
                    except (OSError, ValueError, struct.error):
                        return
                except Exception as e:  # noqa: BLE001 — report, continue
                    try:
                        _send_msg(conn, {"ok": False, "outcome": "invalid",
                                         "error": str(e)})
                    except (OSError, ValueError, struct.error):
                        return
        finally:
            conn.close()

    # --- supervised dispatch ----------------------------------------------

    def _count(self, outcome: str) -> None:
        global_metrics.increment(f"kernel_server.dispatch.{outcome}_total")

    def _dispatch_begin(self, deadline_s) -> int:
        """Register an in-flight dispatch for the health op's wedge
        detection; returns its id for :meth:`_dispatch_end`."""
        from ..utils.sanitize import shared_write
        with self._stats_lock:
            shared_write(self, "_dispatch_seq")
            self._dispatch_seq += 1
            did = self._dispatch_seq
            self._active[did] = (time.monotonic(), deadline_s)
            global_metrics.set_gauge("kernel_server.in_flight",
                                     float(len(self._active)))
        return did

    def _dispatch_end(self, did: int) -> None:
        from ..utils.sanitize import shared_write
        with self._stats_lock:
            shared_write(self, "_active")
            self._active.pop(did, None)
            global_metrics.set_gauge("kernel_server.in_flight",
                                     float(len(self._active)))

    def _supervised(self, op: str, header: dict, arrays: dict):
        """Admission guard → worker-thread dispatch → typed outcome.

        The admission guard has THREE verdicts (r21 mgtier): requests
        whose resident footprint fits the HBM budget run resident;
        graph-shaped requests that exceed it degrade to the STREAMED
        out-of-core path when the streamed working set (O(n) vectors +
        two block buffers) still fits; shed remains the honest answer
        only past that."""
        est = _estimate_request_bytes(header, arrays)
        if op in ("pagerank", "semiring"):
            from ..ops import tier as mgtier
            algorithm = str(header.get("algorithm", "pagerank"))
            n_nodes = int(header.get("n_nodes") or 0)
            n_edges = (int(arrays["src"].shape[0])
                       if "src" in arrays else 0)
            if "src" not in arrays:
                # graph_key-only request: the wire carries no edges, so
                # the request estimate misses the real footprint — size
                # admission off the cached generation's CURRENT edge
                # count or a cached oversized graph would silently ride
                # the resident path past the budget
                # unlocked read-only peek: admission must not queue
                # behind a long dispatch holding _dispatch_lock, and a
                # momentarily stale generation only skews the byte
                # ESTIMATE (the verdict is re-derived next request)
                gen = self._graphs.get(header.get("graph_key"))  # mglint: disable=MG006 — benign unlocked estimate read; blocking admission on _dispatch_lock would defeat the guard
                if gen is not None:
                    n_nodes = n_nodes or gen._n_nodes
                    n_edges = int(np.asarray(gen._coo[0]).shape[0])
                    est = max(est, _graph_footprint_bytes(
                        algorithm, n_nodes, n_edges))
            verdict, est_run = mgtier.admission_verdict(
                est, self.hbm_budget_bytes,
                n_nodes=n_nodes, n_edges=n_edges,
                streamable=algorithm in ("pagerank", "katz", "wcc"),
                precision=str(header.get("precision", "f32")),
                algorithm=algorithm)
            global_metrics.increment(f"tier.admission_{verdict}_total")
            if verdict == "streamed":
                header["_tier_streamed"] = True
                log.info(
                    "kernel_server: STREAMED %s request — resident "
                    "estimate %d bytes exceeds HBM budget %d, streamed "
                    "working set %d bytes fits", op, est,
                    self.hbm_budget_bytes, est_run)
                est = est_run
        if est > self.hbm_budget_bytes:
            self._count("shed")
            global_metrics.increment(
                "kernel_server.admission_rejected_total")
            log.warning(
                "kernel_server: SHED %s request — estimated footprint "
                "%d bytes exceeds HBM budget %d bytes", op, est,
                self.hbm_budget_bytes)
            return ({"ok": False, "outcome": "shed", "retryable": False,
                     "error": f"AdmissionRejected: estimated footprint "
                              f"{est} bytes exceeds HBM budget "
                              f"{self.hbm_budget_bytes} bytes"}, None)

        deadline_s = header.get("deadline_s")
        deadline_s = float(deadline_s) if deadline_s else None
        # trace carrier off the request protocol: the dispatch (and the
        # device stages under it) joins the caller's trace; its spans
        # ship home on the reply (take_trace below)
        carrier = header.pop("trace", None)
        did = self._dispatch_begin(deadline_s or self.wedge_after_s)
        box: dict = {}
        t_dispatch = time.perf_counter()

        def work():
            try:
                # the activation is thread-local; the worker thread must
                # adopt the remote context itself. The stage accumulator
                # collects this dispatch's device attribution (transfer/
                # compile/iterate splits from the mesh entry points);
                # its snapshot ships home in the reply header so the
                # CALLER's PROFILE sees where the HBM-seconds went.
                acc = mgstats.StageAccumulator()
                with mgstats.collecting_stages(acc):
                    with mgtrace.adopt(carrier):
                        with mgtrace.span("kernel.dispatch", op=op,
                                          pid=os.getpid()):
                            with self._dispatch_lock:
                                device_fault_point()
                                box["result"] = self._dispatch_op(
                                    op, header, arrays)
                box["stages"] = acc.snapshot()
            except BaseException as e:  # noqa: BLE001 — classified below
                box["exc"] = e
            finally:
                self._dispatch_end(did)

        def ship_trace(reply: dict) -> dict:
            """Attach this dispatch's spans + stage splits + latency."""
            global_metrics.observe(
                "kernel_server.dispatch_latency_sec",
                time.perf_counter() - t_dispatch,
                trace_id=(carrier or {}).get("trace_id"))
            if carrier and carrier.get("trace_id"):
                spans = mgtrace.take_trace(carrier["trace_id"])
                if spans:
                    reply["trace_spans"] = spans
            stages = box.get("stages")
            if stages:
                reply["stages"] = stages
            return reply

        t = threading.Thread(target=work, daemon=True,
                             name=f"ks-dispatch-{did}")
        t.start()
        t.join(deadline_s)
        if t.is_alive():
            # the dispatch is overdue; it stays in _active, so the
            # health op reports the server as wedged until it finishes
            self._count("deadline_exceeded")
            log.warning("kernel_server: dispatch %d (%s) exceeded its "
                        "%.3fs deadline — device possibly wedged",
                        did, op, deadline_s)
            return ({"ok": False, "outcome": "deadline_exceeded",
                     "retryable": True,
                     "error": f"dispatch exceeded {deadline_s}s "
                              "deadline"}, None)
        if "exc" in box:
            e = box["exc"]
            kind = classify_device_error(e)
            if kind == "oom":
                outcome, retryable = "oom", False
            elif kind in ("device_error", "device_lost"):
                outcome, retryable = "device_error", True
            else:
                outcome, retryable = "invalid", False
            self._count(outcome)
            log.warning("kernel_server: dispatch %d (%s) failed "
                        "[%s]: %s", did, op, outcome, e)
            return (ship_trace({"ok": False, "outcome": outcome,
                                "retryable": retryable,
                                "error": f"{type(e).__name__}: {e}"}),
                    None)
        reply, out_arrays = box["result"]
        if reply.get("ok", True):
            reply.setdefault("outcome", "completed")
            self._count("completed")
        else:
            reply.setdefault("outcome", "invalid")
            self._count("invalid")
        return ship_trace(reply), out_arrays

    def _dispatch_op(self, op: str, header: dict, arrays: dict):
        """Runs under _dispatch_lock on the worker thread."""
        if op == "probe":
            checksum, platform = probe_device()
            return ({"ok": True, "platform": platform,
                     "sum": checksum}, None)
        if op == "semiring":
            return self._op_semiring(header, arrays)
        if op == "lane":
            return self._op_lane(header, arrays)
        return self._op_pagerank(header, arrays)

    def _health_reply(self) -> dict:
        """Liveness + wedge detection + counters; NEVER touches the
        dispatch lock (a wedged dispatch must not wedge health)."""
        from ..utils.sanitize import shared_read
        now = time.monotonic()
        with self._stats_lock:
            shared_read(self, "_active")
            entries = list(self._active.values())
            cached = self._graphs_cached
            platform = self._platform
            shared_read(self, "_modeled_peaks")
            peaks = dict(self._modeled_peaks)
        ages = [now - t0 for t0, _dl in entries]
        wedged = any(dl is not None and now - t0 > dl
                     for t0, dl in entries)
        counters = {name: value for name, _kind, value
                    in global_metrics.snapshot()
                    if name.startswith(("kernel_server.", "analytics.",
                                        "ppr.", "delta.", "lane.",
                                        "tier."))}
        return {"ok": True, "pid": os.getpid(),
                "uptime_s": round(now - self._started, 3),
                "in_flight": len(entries),
                "oldest_dispatch_s": round(max(ages, default=0.0), 3),
                "wedged": wedged,
                "graphs_cached": cached,
                "hbm_budget_bytes": self.hbm_budget_bytes,
                # device memory accounting (mgmem): modeled resident
                # peak per generation (worst-case algorithm columns of
                # the admission table, verified against XLA buffer
                # assignment by tools/mgmem) + the headroom a new
                # request's admission estimate competes for
                "memory": {
                    "hbm_budget_bytes": self.hbm_budget_bytes,
                    "modeled_peak_bytes": sum(peaks.values()),
                    "headroom_bytes": self.hbm_budget_bytes
                    - sum(peaks.values()),
                    "resident_generations": peaks,
                },
                "checkpoint_every": self.checkpoint_every,
                "wedge_after_s": self.wedge_after_s,
                "platform": platform,
                "counters": counters}

    MAX_CACHED_GRAPHS = 8     # LRU cap: the daemon is long-lived and a
    #                           resident generation pins device HBM + host

    def _update_memory_gauge(self) -> None:
        """Recompute the modeled-peak gauge + the per-generation
        snapshot _health_reply serves. Runs under the caller's
        _dispatch_lock (the only _graphs writer); the snapshot is
        handed over under _stats_lock so health never waits behind a
        wedged dispatch."""
        from ..utils.sanitize import shared_write
        peaks = {str(key): _generation_modeled_bytes(g)
                 for key, g in self._graphs.items()}  # mglint: disable=MG006 — under caller's _dispatch_lock (every _graphs mutation site calls this)
        global_metrics.set_gauge("kernel_server.hbm_modeled_peak_bytes",
                                 float(sum(peaks.values())))
        with self._stats_lock:
            shared_write(self, "_modeled_peaks")
            self._modeled_peaks = peaks

    def _resolve_generation(self, header, arrays, place: bool = True):
        """graph_key -> resident-generation lookup shared by every
        graph-shaped op. Runs under _dispatch_lock (see _op_pagerank).
        ``place=False`` (the streamed admission verdict) keeps a fresh
        generation HOST-side: the whole point of the out-of-core path
        is that the edge set never lands on the device at once, so the
        import must not place it either — the generation's lazy
        snapshot and host COO are all the tier needs.

        The generation layer (ops/delta.py, r19 mgdelta): the LRU holds
        :class:`~..ops.delta.ResidentGraph` records keyed
        ``(graph_key, base_version)`` semantics — a request carrying
        ``graph_version``/``base_version`` plus the change-log delta
        payload (``changed`` dense indices + the changed vertices'
        CURRENT incident edges ``inc_src``/``inc_dst``/``inc_w``)
        advances the resident generation O(delta) instead of
        re-importing the full edge list; the request rides the freshly
        spliced graph. A request at the resident version runs directly.
        Returns the ResidentGraph or None (caller replies invalid).
        """
        from ..ops import delta as mgdelta
        from ..ops.csr import from_coo
        from ..utils.sanitize import shared_write
        key = header.get("graph_key")
        want = header.get("graph_version")
        # mglint: disable=MG006 — the dispatcher (_supervised worker) holds _dispatch_lock across this whole handler; intraprocedural analysis cannot see caller locks
        gen = self._graphs.pop(key, None) if key else None
        if gen is not None:
            self._graphs[key] = gen            # re-insert: LRU refresh
        if gen is not None and want is not None \
                and int(want) > gen.version:
            base = header.get("base_version")
            applied = False
            if header.get("has_delta") \
                    and header.get("ids_stable", True) \
                    and base is not None and int(base) == gen.version \
                    and "changed" in arrays and "inc_src" in arrays:
                d = mgdelta.diff_incident(
                    gen.coo, arrays["changed"],
                    arrays["inc_src"], arrays["inc_dst"],
                    arrays.get("inc_w"), gen.n_nodes,
                    int(base), int(want))
                applied = gen.apply(d)
                if applied:
                    # the spliced edge set resizes the generation's
                    # modeled footprint even though the LRU is unchanged
                    self._update_memory_gauge()
            if not applied:
                # stale resident and no usable delta: a full re-import
                # (below) is the only honest path — serving the old
                # generation would return pre-commit results as fresh
                self._graphs.pop(key, None)  # mglint: disable=MG006,MG007 — under caller's _dispatch_lock
                gen = None
                self._update_memory_gauge()
        if gen is None:
            if "src" not in arrays:
                return None
            g = from_coo(arrays["src"].astype(np.int64),
                         arrays["dst"].astype(np.int64),
                         arrays.get("weights"),
                         n_nodes=header.get("n_nodes"))
            if place:
                g = g.to_device()
            gen = mgdelta.ResidentGraph(key, int(want or 0), g)
            if key:
                # mglint: disable=MG006,MG007 — same _dispatch_lock contract as above: the LRU insert+evict runs under the dispatcher's lock
                self._graphs[key] = gen
                while len(self._graphs) > self.MAX_CACHED_GRAPHS:  # mglint: disable=MG006 — under caller's _dispatch_lock
                    self._graphs.pop(next(iter(self._graphs)))  # mglint: disable=MG006,MG007 — under caller's _dispatch_lock
                global_metrics.set_gauge("delta.resident_generations",
                                         float(len(self._graphs)))  # mglint: disable=MG006 — len snapshot under caller's _dispatch_lock
                self._update_memory_gauge()
                with self._stats_lock:
                    shared_write(self, "_graphs_cached")
                    self._graphs_cached = len(self._graphs)  # mglint: disable=MG006 — len snapshot for health; insert path holds _dispatch_lock
        return gen

    def _resolve_graph(self, header, arrays):
        """Back-compat DeviceGraph view of :meth:`_resolve_generation`
        (the PPR batcher and tests consume the snapshot directly)."""
        gen = self._resolve_generation(header, arrays)
        return None if gen is None else gen.graph

    def _op_pagerank(self, header, arrays):
        """Runs under _dispatch_lock; returns (reply_header,
        reply_arrays) for the caller to ship outside the lock. Routes
        through the RESUMABLE mesh entry point (mesh-of-1 unless
        MEMGRAPH_TPU_MESH_DEVICES configures a wider mesh), so a device
        fault mid-run redoes at most checkpoint_every iterations.

        Rides the resident-generation layer (r19 mgdelta): a request at
        a known ``(graph_key, base_version)`` with a delta payload
        refreshes the resident ShardedCSR O(delta) and warm-starts the
        fixpoint from this generation's previous solution — the
        commit-then-CALL path converges in the few iterations the
        perturbation actually needs."""
        from ..ops import delta as mgdelta
        from ..ops import semiring as S
        from ..parallel.mesh import analytics_mesh, get_mesh_context
        streamed = bool(header.pop("_tier_streamed", False))
        gen = self._resolve_generation(header, arrays,
                                       place=not streamed)
        if gen is None:
            return ({"ok": False, "error": "unknown graph_key "
                     "and no edge arrays supplied"}, None)
        key = header.get("graph_key")
        damping = header.get("damping", 0.85)
        tol = header.get("tol", 1e-6)
        precision = header.get("precision", "f32")
        max_iterations = header.get("max_iterations", 100)
        params_key = ("pagerank", float(damping), float(tol),
                      str(precision))
        # unchanged generation + same params: the stored solution is
        # THE answer — identical repeated requests get identical bytes
        hit = gen.cached_result("pagerank", params_key, max_iterations)
        if hit is not None:
            return ({"ok": True, "err": float(hit.err or 0.0),
                     "iters": int(hit.iters or 0), "cache": "hit",
                     "warm_started": True,
                     "graph_version": gen.version},
                    {"ranks": np.asarray(hit.x, dtype=np.float32)})
        x0, _reason = gen.warm_x0("pagerank", params_key)
        if streamed:
            # out-of-core: the edge set never places — blocks stream
            # from the generation's host-pinned paging plan, the rank
            # vector stays device-resident, chunks checkpoint as usual
            from ..parallel.distributed import pagerank_streamed
            t = gen.ensure_tier(precision=_tier_precision(precision))
            ranks, err, iters = pagerank_streamed(
                t, damping=damping, max_iterations=max_iterations,
                tol=tol, x0=x0,
                checkpoint_every=self.checkpoint_every,
                job=f"kernel_server:pagerank:{key}" if key else None)
        else:
            ctx = analytics_mesh() or get_mesh_context(1)
            # run straight off the resident partition-centric variant
            # (the spliced layout) — the DeviceGraph snapshot stays
            # lazy, so a commit costs O(delta), never a CSR rebuild
            scsr = gen.ensure_sharded(ctx, by="src")
            from ..parallel.distributed import pagerank_partition_centric
            with S.backend_extent("mesh"):
                ranks, err, iters = pagerank_partition_centric(
                    scsr, ctx, damping=damping,
                    max_iterations=max_iterations,
                    tol=tol, precision=precision, x0=x0,
                    checkpoint_every=self.checkpoint_every,
                    job=f"kernel_server:pagerank:{key}" if key else None)
        ranks = np.asarray(ranks, dtype=np.float32)
        gen.note_solution("pagerank", params_key, ranks,
                          err=float(err), iters=int(iters),
                          max_iterations=int(max_iterations))
        if x0 is not None:
            mgdelta.record_warm_start("pagerank", int(iters))
        return ({"ok": True, "err": float(err), "iters": int(iters),
                 "warm_started": x0 is not None,
                 "tier": "streamed" if streamed else "resident",
                 "graph_version": gen.version},
                {"ranks": ranks})

    def _op_semiring(self, header, arrays):
        """Semiring-core dispatch: run a named core-routed algorithm at
        a requested precision through the resident runtime.  Serves
        `pagerank` (plus-times, any precision — the bench's
        stage_semiring sweep), `katz`, `wcc`, `labelprop` — all four
        riding the resident-generation warm-start layer (r19 mgdelta,
        per-algorithm contracts in ops/delta.py) — and `bfs` (min-plus
        levels via the GENERIC mesh semiring kernel; source-dependent,
        never warm-started).  Runs under _dispatch_lock."""
        from ..ops import delta as mgdelta
        from ..ops import semiring as S
        from ..parallel import analytics
        from ..parallel.mesh import analytics_mesh, get_mesh_context
        streamed = bool(header.pop("_tier_streamed", False))
        gen = self._resolve_generation(header, arrays,
                                       place=not streamed)
        if gen is None:
            return ({"ok": False, "error": "unknown graph_key "
                     "and no edge arrays supplied"}, None)
        # streamed: never materialize the snapshot — the paging plan
        # (gen.ensure_tier) is built straight off the host COO
        g = None if streamed else gen.graph
        algorithm = header.get("algorithm", "pagerank")
        precision = header.get("precision", "f32")
        max_iterations = header.get("max_iterations", 100)
        if algorithm == "pagerank":
            from ..ops.pagerank import pagerank
            damping = header.get("damping", 0.85)
            tol = header.get("tol", 1e-6)
            params_key = ("pagerank", float(damping), float(tol),
                          str(precision))
            hit = gen.cached_result("pagerank", params_key,
                                    max_iterations)
            if hit is not None:
                return ({"ok": True, "err": float(hit.err or 0.0),
                         "iters": int(hit.iters or 0), "cache": "hit",
                         "algorithm": algorithm,
                         "precision": precision, "warm_started": True,
                         "graph_version": gen.version},
                        {"ranks": np.asarray(hit.x,
                                             dtype=np.float32)})
            x0, _reason = gen.warm_x0("pagerank", params_key)
            if streamed:
                from ..parallel.distributed import pagerank_streamed
                t = gen.ensure_tier(
                    precision=_tier_precision(precision))
                ranks, err, iters = pagerank_streamed(
                    t, damping=damping,
                    max_iterations=max_iterations, tol=tol, x0=x0,
                    checkpoint_every=self.checkpoint_every)
            else:
                # ops-level entry: route_backend picks mesh/mxu/segment
                # and records the per-backend stage PROFILE shows
                ranks, err, iters = pagerank(
                    g, damping=damping, max_iterations=max_iterations,
                    tol=tol, precision=precision, x0=x0)
            ranks = np.asarray(ranks, dtype=np.float32)
            gen.note_solution("pagerank", params_key, ranks,
                              err=float(err), iters=int(iters),
                              max_iterations=int(max_iterations))
            if x0 is not None:
                mgdelta.record_warm_start("pagerank", int(iters))
            return ({"ok": True, "err": float(err), "iters": int(iters),
                     "algorithm": algorithm, "precision": precision,
                     "warm_started": x0 is not None,
                     "tier": "streamed" if streamed else "resident",
                     "graph_version": gen.version},
                    {"ranks": ranks})
        if algorithm == "katz":
            from ..ops.katz import katz_centrality
            alpha = header.get("alpha", 0.2)
            tol = header.get("tol", 1e-6)
            params_key = ("katz", float(alpha),
                          float(header.get("beta", 1.0)), float(tol),
                          str(precision))
            hit = gen.cached_result("katz", params_key, max_iterations)
            if hit is not None:
                return ({"ok": True, "err": float(hit.err or 0.0),
                         "iters": int(hit.iters or 0), "cache": "hit",
                         "algorithm": algorithm,
                         "precision": precision, "warm_started": True,
                         "graph_version": gen.version},
                        {"ranks": np.asarray(hit.x,
                                             dtype=np.float32)})
            x0, _reason = gen.warm_x0("katz", params_key)
            if streamed:
                from ..parallel.distributed import katz_streamed
                t = gen.ensure_tier(
                    precision=_tier_precision(precision))
                xs, err, iters = katz_streamed(
                    t, alpha=alpha, beta=header.get("beta", 1.0),
                    max_iterations=max_iterations, tol=tol, x0=x0,
                    checkpoint_every=self.checkpoint_every)
            else:
                xs, err, iters = katz_centrality(
                    g, alpha=alpha, beta=header.get("beta", 1.0),
                    max_iterations=max_iterations, tol=tol,
                    precision=precision, x0=x0)
            xs = np.asarray(xs, dtype=np.float32)
            gen.note_solution("katz", params_key, xs, err=float(err),
                              iters=int(iters),
                              max_iterations=int(max_iterations))
            if x0 is not None:
                mgdelta.record_warm_start("katz", int(iters))
            return ({"ok": True, "err": float(err), "iters": int(iters),
                     "algorithm": algorithm, "precision": precision,
                     "warm_started": x0 is not None,
                     "tier": "streamed" if streamed else "resident",
                     "graph_version": gen.version},
                    {"ranks": xs})
        if algorithm == "wcc":
            from ..ops.components import weakly_connected_components
            params_key = ("wcc",)
            hit = gen.cached_result("wcc", params_key, max_iterations)
            if hit is not None:
                return ({"ok": True, "iters": int(hit.iters or 0),
                         "cache": "hit", "algorithm": algorithm,
                         "warm_started": True,
                         "graph_version": gen.version},
                        {"components": np.asarray(hit.x,
                                                  dtype=np.int32)})
            comp0, _reason = gen.warm_x0("wcc", params_key)
            if streamed:
                from ..parallel.distributed import wcc_streamed
                t = gen.ensure_tier(precision="f32")
                comp, _changed, iters = wcc_streamed(
                    t, max_iterations=max_iterations, comp0=comp0,
                    checkpoint_every=self.checkpoint_every)
            else:
                comp, iters = weakly_connected_components(
                    g, max_iterations=max_iterations, comp0=comp0)
            comp = np.asarray(comp, dtype=np.int32)
            gen.note_solution("wcc", params_key, comp,
                              iters=int(iters),
                              max_iterations=int(max_iterations))
            if comp0 is not None:
                mgdelta.record_warm_start("wcc", int(iters))
            return ({"ok": True, "iters": int(iters),
                     "algorithm": algorithm,
                     "warm_started": comp0 is not None,
                     "tier": "streamed" if streamed else "resident",
                     "graph_version": gen.version},
                    {"components": comp})
        if algorithm == "labelprop":
            from ..ops.labelprop import label_propagation
            self_weight = header.get("self_weight", 0.0)
            directed = bool(header.get("directed", False))
            params_key = ("labelprop", float(self_weight), directed)
            hit = gen.cached_result("labelprop", params_key,
                                    max_iterations)
            if hit is not None:
                return ({"ok": True, "iters": int(hit.iters or 0),
                         "cache": "hit", "algorithm": algorithm,
                         "warm_started": True,
                         "graph_version": gen.version},
                        {"labels": np.asarray(hit.x, dtype=np.int32)})
            labels0, _reason = gen.warm_x0("labelprop", params_key)
            labels, iters = label_propagation(
                g, max_iterations=max_iterations,
                self_weight=self_weight, directed=directed,
                labels0=labels0)
            labels = np.asarray(labels, dtype=np.int32)
            gen.note_solution("labelprop", params_key, labels,
                              iters=int(iters),
                              max_iterations=int(max_iterations))
            if labels0 is not None:
                mgdelta.record_warm_start("labelprop", int(iters))
            return ({"ok": True, "iters": int(iters),
                     "algorithm": algorithm,
                     "warm_started": labels0 is not None,
                     "graph_version": gen.version},
                    {"labels": labels})
        if algorithm == "bfs":
            ctx = analytics_mesh() or get_mesh_context(1)
            with S.backend_extent("mesh"):
                levels, iters = analytics.bfs_mesh(
                    g, ctx, int(header.get("source", 0)),
                    max_iterations=max_iterations, precision=precision,
                    checkpoint_every=self.checkpoint_every)
            return ({"ok": True, "iters": int(iters),
                     "algorithm": algorithm, "precision": precision},
                    {"levels": np.asarray(levels, dtype=np.int32)})
        return ({"ok": False,
                 "error": f"unknown semiring algorithm {algorithm!r}"},
                None)

    def _op_lane(self, header, arrays):
        """Compiled read-lane hop-count dispatch (r20 mglane): the same
        masked plus_first SpMV chain the in-process lane runs
        (ops/pipeline.py hop_counts), served from the resident device
        plane so OLTP frontends can route their compiled expansions
        like any analytics op. Runs under _dispatch_lock."""
        from ..ops import pipeline as pl
        for need in ("src", "dst", "emask", "smask", "midmask", "tmask"):
            if need not in arrays:
                return ({"ok": False,
                         "error": f"lane op needs array {need!r}"}, None)
        global_metrics.increment("lane.remote_dispatch_total")
        try:
            totals = pl.hop_counts(
                arrays["src"], arrays["dst"], arrays["emask"],
                arrays["smask"], arrays["midmask"], arrays["tmask"],
                int(header.get("n_nodes", len(arrays["smask"]))),
                hops=int(header.get("hops", 2)),
                include_lower=bool(header.get("include_lower", False)),
                edge_unique=bool(header.get("edge_unique", True)),
                need_rows=bool(header.get("need_rows", True)),
                need_distinct=bool(header.get("need_distinct", False)),
                fingerprint=header.get("fingerprint"))
        except pl.LaneRefused as e:
            return ({"ok": False, "outcome": "invalid",
                     "lane_refused": e.reason,
                     "error": f"lane refused: {e.reason}"}, None)
        return ({"ok": True, **totals}, None)


# --------------------------------------------------------------------------
# client
# --------------------------------------------------------------------------

class KernelClient:
    def __init__(self, socket_path: str = DEFAULT_SOCKET,
                 timeout: float = 300.0) -> None:
        self.socket_path = socket_path
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.settimeout(timeout)
        self._sock.connect(socket_path)

    def settimeout(self, timeout: float | None) -> None:
        self._sock.settimeout(timeout)

    def call(self, header: dict, arrays=None):
        _send_msg(self._sock, header, arrays)
        h, out = _recv_msg(self._sock)
        # spans the server recorded for OUR trace come home on the
        # reply; adopt them so the retained trace is connected
        spans = h.pop("trace_spans", None)
        if spans:
            mgtrace.adopt_spans(spans)
        # same for the dispatch's device-stage splits: merge into the
        # caller's active stage accumulator (PROFILE attribution)
        mgstats.merge_stages(h.pop("stages", None))
        return h, out

    def ping(self) -> bool:
        try:
            h, _ = self.call({"op": "ping"})
            return bool(h.get("ok"))
        except (OSError, ConnectionError):
            return False

    def health(self) -> dict:
        h, _ = self.call({"op": "health"})
        return h

    def probe(self) -> dict:
        """Typed device probe through the resident runtime."""
        header = {"op": "probe"}
        carrier = mgtrace.inject()
        if carrier is not None:
            header["trace"] = carrier
        h, _ = self.call(header)
        return h

    @staticmethod
    def _serving_arrays(arrays: dict, changed, inc_src, inc_dst,
                        inc_w) -> None:
        """Attach the analytics serving-plane delta payload (r19
        mgdelta): the change-log's dense changed indices plus the
        changed vertices' CURRENT incident edges — the server diffs
        them against its resident generation and refreshes O(delta)."""
        if changed is not None:
            arrays["changed"] = np.asarray(changed, dtype=np.int32)
        if inc_src is not None:
            arrays["inc_src"] = np.asarray(inc_src, dtype=np.int64)
            arrays["inc_dst"] = np.asarray(inc_dst, dtype=np.int64)
            if inc_w is not None:
                arrays["inc_w"] = np.asarray(inc_w, dtype=np.float32)

    def pagerank(self, src=None, dst=None, weights=None, n_nodes=None,
                 graph_key=None, deadline_s=None, graph_version=None,
                 base_version=None, ids_stable=True, changed=None,
                 inc_src=None, inc_dst=None, inc_w=None, **params):
        arrays = {}
        if src is not None:
            arrays["src"] = np.asarray(src, dtype=np.int64)
            arrays["dst"] = np.asarray(dst, dtype=np.int64)
            if weights is not None:
                arrays["weights"] = np.asarray(weights, dtype=np.float32)
        self._serving_arrays(arrays, changed, inc_src, inc_dst, inc_w)
        header = {"op": "pagerank", "graph_key": graph_key,
                  "n_nodes": n_nodes, **params}
        if graph_version is not None:
            header["graph_version"] = int(graph_version)
            header["base_version"] = base_version
            header["ids_stable"] = bool(ids_stable)
            header["has_delta"] = changed is not None
        if deadline_s is not None:
            header["deadline_s"] = deadline_s
        carrier = mgtrace.inject()
        if carrier is not None:
            header["trace"] = carrier
        h, out = self.call(header, arrays)
        if not h.get("ok"):
            _raise_for_reply(h)
        return out["ranks"], h["err"], h["iters"]

    def ppr(self, sources, src=None, dst=None, weights=None, n_nodes=None,
            graph_key=None, graph_version=0, base_version=None,
            ids_stable=True, changed=None, inc_src=None, inc_dst=None,
            inc_w=None, top_k=0, damping=0.85,
            tol=1e-6, max_iterations=100, precision="f32",
            deadline_s=None):
        """One personalized-PageRank request through the server's
        COALESCING plane: concurrent callers batch into one multi-source
        SpMM fixpoint; repeats hit the change-log-invalidated result
        cache. Returns (reply_header, arrays) — arrays carry either
        ``ranks`` (top_k == 0) or ``topk_val``/``topk_idx``.

        ``graph_version``/``base_version``/``changed``/``ids_stable``
        are the cache-invalidation protocol: ``changed`` lists the dense
        node indices mutated in (base_version, graph_version] (from the
        storage change log); omitted → the server conservatively
        invalidates every cached vector for this graph_key on a version
        bump. ``inc_src``/``inc_dst``/``inc_w`` (r19 mgdelta) carry the
        changed vertices' CURRENT incident edges so the server can
        refresh its resident snapshot O(delta) instead of needing the
        full edge arrays after every commit."""
        arrays = {"sources": np.asarray(sources, dtype=np.int32)}
        if src is not None:
            arrays["src"] = np.asarray(src, dtype=np.int64)
            arrays["dst"] = np.asarray(dst, dtype=np.int64)
            if weights is not None:
                arrays["weights"] = np.asarray(weights, dtype=np.float32)
        self._serving_arrays(arrays, changed, inc_src, inc_dst, inc_w)
        header = {"op": "ppr", "graph_key": graph_key, "n_nodes": n_nodes,
                  "graph_version": int(graph_version),
                  "base_version": base_version,
                  "ids_stable": bool(ids_stable),
                  "has_delta": changed is not None,
                  "damping": float(damping), "tol": float(tol),
                  "max_iterations": int(max_iterations),
                  "precision": str(precision), "top_k": int(top_k)}
        if deadline_s is not None:
            header["deadline_s"] = deadline_s
        carrier = mgtrace.inject()
        if carrier is not None:
            header["trace"] = carrier
        h, out = self.call(header, arrays)
        if not h.get("ok"):
            _raise_for_reply(h)
        return h, out

    def semiring(self, algorithm: str = "pagerank", src=None, dst=None,
                 weights=None, n_nodes=None, graph_key=None,
                 precision: str = "f32", deadline_s=None,
                 graph_version=None, base_version=None, ids_stable=True,
                 changed=None, inc_src=None, inc_dst=None, inc_w=None,
                 **params):
        """Run a semiring-core-routed algorithm on the resident daemon.
        Returns the reply header + arrays dict (algorithm-shaped:
        pagerank/katz -> ranks/err/iters, wcc -> components/iters,
        labelprop -> labels/iters, bfs -> levels/iters). The
        graph_version/base_version/changed/inc_* kwargs are the r19
        delta protocol (see :meth:`pagerank`)."""
        arrays = {}
        if src is not None:
            arrays["src"] = np.asarray(src, dtype=np.int64)
            arrays["dst"] = np.asarray(dst, dtype=np.int64)
            if weights is not None:
                arrays["weights"] = np.asarray(weights, dtype=np.float32)
        self._serving_arrays(arrays, changed, inc_src, inc_dst, inc_w)
        header = {"op": "semiring", "algorithm": algorithm,
                  "graph_key": graph_key, "n_nodes": n_nodes,
                  "precision": precision, **params}
        if graph_version is not None:
            header["graph_version"] = int(graph_version)
            header["base_version"] = base_version
            header["ids_stable"] = bool(ids_stable)
            header["has_delta"] = changed is not None
        if deadline_s is not None:
            header["deadline_s"] = deadline_s
        carrier = mgtrace.inject()
        if carrier is not None:
            header["trace"] = carrier
        h, out = self.call(header, arrays)
        if not h.get("ok"):
            _raise_for_reply(h)
        return h, out

    def lane_hops(self, src, dst, emask, smask, midmask, tmask, *,
                  n_nodes, hops=2, include_lower=False, edge_unique=True,
                  need_rows=True, need_distinct=False, deadline_s=None,
                  fingerprint=None) -> dict:
        """Dispatch one compiled read-lane hop-count program (r20
        mglane) on the resident daemon. The server refuses with a typed
        reason exactly like the in-process lane; the caller's LOUD
        fallback contract is identical. Returns {"rows": n,
        "distinct": n} per request flags."""
        from ..ops.pipeline import LaneRefused
        arrays = {"src": np.asarray(src, dtype=np.int32),
                  "dst": np.asarray(dst, dtype=np.int32),
                  "emask": np.asarray(emask, dtype=bool),
                  "smask": np.asarray(smask, dtype=bool),
                  "midmask": np.asarray(midmask, dtype=np.float32),
                  "tmask": np.asarray(tmask, dtype=np.float32)}
        header = {"op": "lane", "n_nodes": int(n_nodes),
                  "hops": int(hops),
                  "include_lower": bool(include_lower),
                  "edge_unique": bool(edge_unique),
                  "need_rows": bool(need_rows),
                  "need_distinct": bool(need_distinct),
                  "fingerprint": fingerprint}
        if deadline_s is not None:
            header["deadline_s"] = deadline_s
        carrier = mgtrace.inject()
        if carrier is not None:
            header["trace"] = carrier
        h, _out = self.call(header, arrays)
        if not h.get("ok"):
            if h.get("lane_refused"):
                raise LaneRefused(h["lane_refused"],
                                  h.get("error", ""))
            _raise_for_reply(h)
        return {k: int(v) for k, v in h.items()
                if k in ("rows", "distinct")}

    def shutdown(self) -> None:
        try:
            self.call({"op": "shutdown"})
        except (OSError, ConnectionError):
            pass

    def close(self) -> None:
        self._sock.close()


# --------------------------------------------------------------------------
# client-side supervisor
# --------------------------------------------------------------------------

class SupervisedKernelClient:
    """Supervised access to the resident kernel server.

    Wraps :class:`KernelClient` with the client half of the resilience
    contract:

      * requests carry a per-request ``deadline_s`` and retry under a
        shared :class:`RetryPolicy` (per-attempt timeout + overall
        deadline) — but ONLY idempotent ones; non-idempotent calls
        surface the first typed failure;
      * connection loss (the daemon died — e.g. device.lost killed it)
        respawns the server via :func:`ensure_server` and retries;
      * ``check_once()`` (and the optional background health loop)
        polls the ``health`` op and RESTARTS a wedged or unreachable
        server process — SIGKILL + respawn; the daemon's stale-socket
        reclaim logic makes that safe;
      * typed non-retryable outcomes (AdmissionRejected, KernelOom)
        propagate immediately.
    """

    def __init__(self, socket_path: str = DEFAULT_SOCKET,
                 retry: RetryPolicy | None = None,
                 spawn_timeout_s: float = 120.0,
                 idle_timeout_s: float = 900.0,
                 deadline_s: float | None = None,
                 spawn: bool = True) -> None:
        import threading
        from ..utils.locks import tracked_lock
        from ..utils.sanitize import shared_field
        self.socket_path = socket_path
        self.retry = retry or RetryPolicy(
            base_delay=0.2, max_delay=2.0, max_retries=4,
            attempt_timeout=300.0)
        self.spawn_timeout_s = spawn_timeout_s
        self.idle_timeout_s = idle_timeout_s
        self.deadline_s = deadline_s
        self.spawn = spawn
        # leaf lock guarding the (client, pid) pair: swapped by the
        # caller thread AND the health loop; network I/O always happens
        # OUTSIDE it
        self._state_lock = tracked_lock("SupervisedKernelClient._state_lock")
        self._client: KernelClient | None = None
        self._pid: int | None = None
        self._stop = threading.Event()
        self._health_thread = None
        shared_field(self, "_client", "_pid")

    # --- connection management ---------------------------------------------

    def _install(self, client: KernelClient | None):
        from ..utils.sanitize import shared_write
        with self._state_lock:
            shared_write(self, "_client")
            old, self._client = self._client, client
        if old is not None:
            try:
                old.close()
            except OSError as e:
                log.debug("closing stale kernel client: %s", e)
        return client

    def _current(self) -> KernelClient | None:
        from ..utils.sanitize import shared_read
        with self._state_lock:
            shared_read(self, "_client")
            return self._client

    def _set_pid(self, pid: int | None) -> None:
        from ..utils.sanitize import shared_write
        with self._state_lock:
            shared_write(self, "_pid")
            self._pid = pid

    def _get_pid(self) -> int | None:
        from ..utils.sanitize import shared_read
        with self._state_lock:
            shared_read(self, "_pid")
            return self._pid

    def _connect(self) -> KernelClient:
        c = self._current()
        if c is not None:
            return c
        timeout = self.retry.attempt_timeout or 300.0
        if self.spawn:
            c = ensure_server(self.socket_path,
                              spawn_timeout_s=self.spawn_timeout_s,
                              idle_timeout_s=self.idle_timeout_s)
            if c is None:
                raise ConnectionError(
                    "kernel server spawn starved (no responder within "
                    f"{self.spawn_timeout_s}s)")
            c.settimeout(timeout)
        else:
            c = KernelClient(self.socket_path, timeout=timeout)
        try:
            h, _ = c.call({"op": "ping"})
            self._set_pid(h.get("pid"))
        except (OSError, ConnectionError) as e:
            log.debug("post-connect ping failed: %s", e)
        return self._install(c)

    def _drop(self) -> None:
        self._install(None)

    # --- supervision --------------------------------------------------------

    def health(self, timeout: float = 5.0) -> dict | None:
        """The daemon's health reply over a FRESH connection (a wedged
        request stream must not block the health probe), or None when
        nothing answers."""
        try:
            c = KernelClient(self.socket_path, timeout=timeout)
        except OSError:
            return None
        try:
            return c.health()
        except (OSError, ConnectionError):
            return None
        finally:
            try:
                c.close()
            except OSError as e:
                log.debug("closing health probe connection: %s", e)

    def _mirror_daemon_counters(self, h: dict) -> None:
        """Publish the daemon's health-reply counters through the LOCAL
        global Metrics registry so the supervisor's prometheus_text()
        carries them (restarts, sheds, deadline_exceeded, oom, ...) —
        not only callers of the ``health`` op. Gauges, not counters:
        they mirror another process's monotonic state and must not
        double-count across supervision rounds."""
        for name, value in (h.get("counters") or {}).items():
            short = name[len("kernel_server."):] \
                if name.startswith("kernel_server.") else name
            global_metrics.set_gauge(f"kernel_server.daemon.{short}",
                                     float(value))
        global_metrics.set_gauge("kernel_server.daemon.in_flight",
                                 float(h.get("in_flight", 0)))
        global_metrics.set_gauge("kernel_server.daemon.wedged",
                                 1.0 if h.get("wedged") else 0.0)

    def check_once(self) -> str:
        """One supervision round: health-check, restart when wedged or
        unreachable. Returns "ok" or "restarted"."""
        global_metrics.increment(
            "kernel_server.supervisor.health_checks_total")
        h = self.health()
        if h is None:
            self.restart_server(reason="unreachable")
            return "restarted"
        self._mirror_daemon_counters(h)
        if h.get("wedged"):
            global_metrics.increment(
                "kernel_server.supervisor.wedge_detected_total")
            self.restart_server(reason="wedged", pid=h.get("pid"))
            return "restarted"
        self._set_pid(h.get("pid"))
        return "ok"

    def restart_server(self, reason: str = "manual",
                       pid: int | None = None) -> None:
        """Kill the (wedged / device-lost) daemon and let the next call
        respawn it. The daemon's probe-then-bind + stale-socket reclaim
        makes the SIGKILL safe: the successor reclaims the path."""
        pid = pid or self._get_pid()
        self._drop()
        self._set_pid(None)
        if pid and pid != os.getpid():
            try:
                os.kill(pid, signal.SIGKILL)
            except (OSError, ProcessLookupError) as e:
                log.debug("kernel server pid %s already gone: %s", pid, e)
        global_metrics.increment("kernel_server.supervisor.restarts_total")
        log.warning("kernel_server supervisor: restarting server "
                    "(reason=%s pid=%s)", reason, pid)

    def start_health_loop(self, interval_s: float = 5.0) -> None:
        """Background supervision: health-check every interval_s,
        restarting a wedged/lost daemon. Idempotent."""
        import threading
        if self._health_thread is not None:
            return

        def loop():
            while not self._stop.wait(interval_s):
                try:
                    self.check_once()
                except Exception:  # noqa: BLE001 — supervision must survive
                    log.exception("kernel_server supervisor health "
                                  "check failed")

        self._health_thread = threading.Thread(
            target=loop, daemon=True, name="ks-supervisor")
        self._health_thread.start()

    # --- supervised calls ---------------------------------------------------

    def _call_supervised(self, op: str, invoke, idempotent: bool):
        """The shared supervised-retry skeleton: ``invoke(client)`` runs
        under the retry policy with the typed-outcome branching every
        supervised op shares (pagerank, ppr, ...)."""
        last: Exception | None = None
        for _attempt in self.retry.attempts():
            try:
                c = self._connect()
                t0 = time.perf_counter()
                with mgtrace.span("kernel.request", op=op,
                                  attempt=_attempt):
                    result = invoke(c)
                # client-observed dispatch wall time (request + device +
                # reply) for the caller's PROFILE attribution
                mgstats.record_stage("kernel_dispatch",
                                     time.perf_counter() - t0)
                return result
            except (AdmissionRejected, KernelOom):
                # deterministic against this budget/graph: retry is noise
                raise
            except KernelDeadlineExceeded as e:
                last = e
                if not idempotent:
                    raise
                global_metrics.increment(
                    "kernel_server.client.retries_total")
                self.check_once()    # a wedged server gets restarted here
            except KernelDeviceError as e:
                last = e
                if not idempotent:
                    raise
                global_metrics.increment(
                    "kernel_server.client.retries_total")
            except (ConnectionError, OSError) as e:
                # daemon gone (device.lost kill) or socket timed out:
                # drop the connection; _connect respawns when allowed
                last = e
                self._drop()
                if not idempotent:
                    raise
                global_metrics.increment(
                    "kernel_server.client.retries_total")
        raise KernelServerError(
            f"kernel request failed after {self.retry.max_retries + 1} "
            f"supervised attempts: {last}",
            outcome=getattr(last, "outcome", "invalid"),
            retryable=False) from last

    def pagerank(self, src=None, dst=None, weights=None, n_nodes=None,
                 graph_key=None, idempotent: bool = True,
                 deadline_s: float | None = None, **params):
        """PageRank with supervised retries. Pure computation ⇒
        idempotent by default; callers piping through side-effecting
        wrappers pass idempotent=False and get fail-fast semantics."""
        if deadline_s is None:
            deadline_s = self.deadline_s
        return self._call_supervised(
            "pagerank",
            lambda c: c.pagerank(src=src, dst=dst, weights=weights,
                                 n_nodes=n_nodes, graph_key=graph_key,
                                 deadline_s=deadline_s, **params),
            idempotent)

    def lane_hops(self, src, dst, emask, smask, midmask, tmask, *,
                  n_nodes, idempotent: bool = True,
                  deadline_s: float | None = None, **params):
        """Compiled read-lane hop counts with supervised retries (r20
        mglane). Pure computation ⇒ idempotent; LaneRefused passes
        through untouched so the caller's typed fallback fires."""
        if deadline_s is None:
            deadline_s = self.deadline_s
        # a typed LaneRefused from the reply propagates untouched (it
        # is not one of the supervised retry classes), so the caller's
        # loud fallback fires instead of a retry storm
        return self._call_supervised(
            "lane",
            lambda c: c.lane_hops(src, dst, emask, smask, midmask,
                                  tmask, n_nodes=n_nodes,
                                  deadline_s=deadline_s, **params),
            idempotent)

    def ppr(self, sources, idempotent: bool = True,
            deadline_s: float | None = None, **params):
        """Coalesced personalized PageRank with supervised retries (see
        :meth:`KernelClient.ppr` for the serving protocol). Pure
        computation ⇒ idempotent by default; a device fault mid-batch
        fails every rider typed, so the retry here re-enters the
        coalescing queue cleanly."""
        if deadline_s is None:
            deadline_s = self.deadline_s
        return self._call_supervised(
            "ppr",
            lambda c: c.ppr(sources, deadline_s=deadline_s, **params),
            idempotent)

    def close(self) -> None:
        self._stop.set()
        if self._health_thread is not None:
            self._health_thread.join(timeout=10)
            self._health_thread = None
        self._drop()


def ensure_server(socket_path: str = DEFAULT_SOCKET,
                  spawn_timeout_s: float = 120.0,
                  idle_timeout_s: float = 900.0):
    """Connect to the resident server, spawning it if absent.

    Returns a connected KernelClient, or None when the spawn TIMED OUT
    (the stillborn daemon is killed so it cannot keep competing for
    CPU). A daemon that DIED during init raises RuntimeError — that is
    a real regression, not an environmental condition, and callers'
    skip/fallback paths must not mask it."""
    try:
        c = KernelClient(socket_path, timeout=spawn_timeout_s)
        if c.ping():
            return c
        c.close()
    except OSError:
        pass
    proc = subprocess.Popen(
        [sys.executable, "-m", "memgraph_tpu.server.kernel_server",
         "--socket", socket_path, "--idle-timeout", str(idle_timeout_s)],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        start_new_session=True)   # survives the spawning client
    deadline = time.monotonic() + spawn_timeout_s
    while time.monotonic() < deadline:
        # keep polling the socket even if OUR child died: in a spawn
        # race the loser exits after probing a live responder (or on the
        # bind conflict) while the winner is still importing jax — its
        # server arrives soon
        try:
            c = KernelClient(socket_path, timeout=spawn_timeout_s)
            if c.ping():
                return c
            c.close()
        except OSError:
            time.sleep(0.1)
    if proc.poll() is not None:
        # nobody ever served AND our daemon died: a real init failure
        # (import error, crash), not environmental starvation
        raise RuntimeError(
            f"kernel server died during init (rc={proc.returncode})")
    try:
        proc.kill()               # a starved spawn must not linger
        proc.wait(timeout=10)
    except (OSError, subprocess.TimeoutExpired):
        pass
    return None


#: per-socket supervised clients shared process-wide (a client owns a
#: connection + supervision state; one per daemon is the contract)
_SHARED_CLIENTS: dict = {}
_shared_clients_guard = threading.Lock()


def shared_client(socket_path: str = DEFAULT_SOCKET,
                  spawn: bool = False) -> SupervisedKernelClient:
    """The process-wide SupervisedKernelClient for a socket — ops-level
    kernel routing (ops/pagerank.py) and the procedure layer share one
    supervisor per daemon instead of each minting connections."""
    with _shared_clients_guard:
        client = _SHARED_CLIENTS.get(socket_path)
        if client is None:
            client = _SHARED_CLIENTS[socket_path] = \
                SupervisedKernelClient(socket_path, spawn=spawn)
        return client


def main() -> None:
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--socket", default=DEFAULT_SOCKET)
    ap.add_argument("--idle-timeout", type=float, default=900.0)
    args = ap.parse_args()
    from ..utils.jax_cache import honor_jax_platforms_env
    honor_jax_platforms_env()
    KernelServer(args.socket, idle_timeout_s=args.idle_timeout).serve_forever()


if __name__ == "__main__":
    main()
