"""mgstat: per-query resource accounting, workload fingerprint
statistics, and the cluster-wide saturation plane.

Counterpart of the reference's query statistics / `SHOW` surfaces and
USE-style saturation accounting, built on the mgtrace substrate (PR 8):

* **Query fingerprints** — every Cypher query is normalized to a
  literal-stripped, parameter-normalized *shape* (``fingerprint_text``),
  cached alongside the plan cache so repeat queries pay one dict lookup.
  Per-fingerprint statistics (count, errors, latency histogram, rows,
  plan-cache hit rate, retained trace ids) live in a bounded
  **space-saving top-K** registry (:class:`QueryStatsRegistry`): when
  the table is full the minimum-count entry is evicted and the newcomer
  inherits its count (the classic Metwally et al. guarantee — counts
  are exact while distinct shapes ≤ K, and overestimates are bounded by
  the evicted minimum afterwards). Surfaced as ``SHOW QUERY STATS`` and
  ``GET /stats``.

* **Device-stage attribution** — a thread-local
  :class:`StageAccumulator` collects where device seconds went
  (``kernel_dispatch`` / ``device_transfer`` / ``device_compile`` /
  ``device_iterate``). The analytics entry points and the checkpoint
  runner record into whichever accumulator is active; a kernel-server
  dispatch collects on its worker thread and ships the result home in
  the reply header (``stages``), which the client merges into ITS
  active accumulator — so ``PROFILE`` on a device-routed query shows
  HBM-seconds regardless of which process ran the kernel. Disarmed
  (no accumulator active) every hook is one thread-local read.

* **Saturation plane** — :class:`SaturationPlane` folds the USE-style
  gauges every bounded resource already exports (bolt session pool,
  mp-executor in-flight, kernel-server in-flight/shed/HBM budget, WAL
  fsync backlog, replication lag) into one machine-readable readiness
  verdict for ``GET /health``: ``{"ready": bool, "reasons": [...]}``
  where each reason names the saturated resource, the observed value,
  and the threshold. Error-class signals (kernel sheds, replication
  rpc failures) are rate-based: the verdict trips when the counter
  moved since the previous evaluation, mirroring USE's "errors" axis.

* **Scrape federation** — :func:`federate_expositions` merges several
  instances' ``prometheus_text()`` payloads into one exposition with
  ``instance`` labels injected per sample (exemplars preserved, one
  ``# TYPE`` line per family), which the coordinator serves for the
  whole cluster (main + replicas + kernel daemon).

Everything here is process-global (like ``metrics.global_metrics``)
and cheap by default; ``MEMGRAPH_TPU_STATS=0`` disables fingerprint
collection outright.
"""

from __future__ import annotations

import os
import re
import threading
import time
from collections import deque

from ..utils.locks import tracked_lock
from ..utils.sanitize import shared_field, shared_read, shared_write
from .metrics import Histogram, global_metrics

ENV_DISABLE = "MEMGRAPH_TPU_STATS"          # "0" disables collection
ENV_TOPK = "MEMGRAPH_TPU_STATS_TOPK"        # top-K capacity (default 128)
ENV_MAX_LAG = "MEMGRAPH_TPU_HEALTH_MAX_REPL_LAG"        # txns (default 1000)
ENV_MAX_BACKLOG = "MEMGRAPH_TPU_HEALTH_MAX_FSYNC_BACKLOG"  # bytes (64 MiB)
ENV_MAX_PPR_QUEUE = "MEMGRAPH_TPU_HEALTH_MAX_PPR_QUEUE"  # pending (192)
ENV_MAX_SHARD_QUEUE = "MEMGRAPH_TPU_HEALTH_MAX_SHARD_QUEUE"  # depth (16)
ENV_MAX_STREAM_LAG = "MEMGRAPH_TPU_HEALTH_MAX_STREAM_LAG"  # units (100000)

#: every device stage the accumulator may carry — the attribution
#: vocabulary PROFILE and BENCH records share. The ``lane_*`` stages
#: are the compiled read lane's split (r20 mglane): program build /
#: host staging + upload / device execution, so PROFILE on a
#: lane-served query shows where its milliseconds went.
STAGE_NAMES = ("kernel_dispatch", "device_transfer", "device_compile",
               "device_iterate", "lane_compile", "lane_dispatch",
               "lane_iterate")


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


# --------------------------------------------------------------------------
# query fingerprinting
# --------------------------------------------------------------------------

_STRING_LIT = re.compile(r"'(?:[^'\\]|\\.)*'|\"(?:[^\"\\]|\\.)*\"")
_PARAM = re.compile(r"\$\w+")
_NUMBER = re.compile(r"\b\d+(?:\.\d+)?(?:[eE][+-]?\d+)?\b")
_WS = re.compile(r"\s+")


def fingerprint_text(text: str) -> str:
    """Literal-stripped, parameter-normalized query shape.

    Two queries differing only in literal values or parameter names map
    to the same fingerprint; label/property/identifier case is kept
    (labels are case-sensitive, so folding would merge distinct shapes).
    The fingerprint never contains literal values — it is safe to log
    and expose (same contract as the slow-query log's redaction).
    """
    s = _STRING_LIT.sub("?", text)
    s = _PARAM.sub("$?", s)
    s = _NUMBER.sub("?", s)
    s = _WS.sub(" ", s).strip()
    # PROFILE/EXPLAIN wrap a shape, they are not one: a profiled run
    # increments the SAME fingerprint as the plain query (the
    # interpreter strips the keyword for plan-cache keying identically)
    head, _, rest = s.partition(" ")
    if head.upper() in ("PROFILE", "EXPLAIN") and rest:
        return rest
    return s


class _Entry:
    """One fingerprint's accumulated statistics."""

    __slots__ = ("fingerprint", "count", "errors", "overcount",
                 "plan_cache_hits", "rows_total", "latency", "trace_ids",
                 "first_seen", "last_seen")

    def __init__(self, fingerprint: str, overcount: int = 0) -> None:
        self.fingerprint = fingerprint
        self.count = overcount          # space-saving: inherited minimum
        self.overcount = overcount      # error bound on `count`
        self.errors = 0
        self.plan_cache_hits = 0
        self.rows_total = 0
        self.latency = Histogram()
        #: most recent trace ids observed while tracing was armed — the
        #: link from a hot fingerprint to retained traces in /traces
        self.trace_ids: deque = deque(maxlen=8)
        self.first_seen = time.time()
        self.last_seen = self.first_seen


class QueryStatsRegistry:
    """Bounded per-fingerprint statistics (space-saving top-K).

    All mutation happens under one leaf lock; `record()` is the per-
    query hot path and does one dict lookup + one histogram observe.
    """

    def __init__(self, capacity: int | None = None) -> None:
        self.capacity = capacity if capacity is not None \
            else max(8, _env_int(ENV_TOPK, 128))
        self._enabled = os.environ.get(ENV_DISABLE, "") != "0"
        self._lock = tracked_lock("QueryStatsRegistry._lock")
        self._entries: dict[str, _Entry] = {}
        #: query text -> fingerprint memo (the plan-cache analog: repeat
        #: query texts never re-run the normalization regexes)
        self._fp_cache: dict[str, str] = {}
        shared_field(self, "_entries", "_fp_cache")

    # --- arming -------------------------------------------------------------

    def enabled(self) -> bool:
        return self._enabled

    def enable(self) -> None:
        self._enabled = True

    def disable(self) -> None:
        self._enabled = False

    def reset(self) -> None:
        with self._lock:
            shared_write(self, "_entries")
            self._entries.clear()
            self._fp_cache.clear()

    # --- recording ----------------------------------------------------------

    def fingerprint(self, text: str) -> str:
        """Memoized fingerprint of a query text (bounded memo)."""
        with self._lock:
            shared_read(self, "_fp_cache")
            hit = self._fp_cache.get(text)
        if hit is not None:
            return hit
        fp = fingerprint_text(text)
        with self._lock:
            shared_write(self, "_fp_cache")
            if len(self._fp_cache) < 1024:
                self._fp_cache[text] = fp
        return fp

    def record(self, fingerprint: str, latency_s: float, rows: int = 0,
               error: bool = False, plan_cache_hit: bool = False,
               trace_id: str | None = None) -> None:
        if not self._enabled:
            return
        with self._lock:
            shared_write(self, "_entries")
            entry = self._entries.get(fingerprint)
            if entry is None:
                if len(self._entries) >= self.capacity:
                    # space-saving eviction: replace the minimum-count
                    # entry; the newcomer inherits its count as both the
                    # starting value and the documented overcount bound
                    victim = min(self._entries.values(),
                                 key=lambda e: e.count)
                    del self._entries[victim.fingerprint]
                    entry = _Entry(fingerprint, overcount=victim.count)
                    global_metrics.increment("mgstat.evictions_total")
                else:
                    entry = _Entry(fingerprint)
                self._entries[fingerprint] = entry
            entry.count += 1
            entry.last_seen = time.time()
            if error:
                entry.errors += 1
            if plan_cache_hit:
                entry.plan_cache_hits += 1
            entry.rows_total += int(rows)
            entry.latency.observe(latency_s, trace_id)
            if trace_id:
                entry.trace_ids.append(trace_id)

    def record_text(self, text: str, latency_s: float, rows: int = 0,
                    error: bool = False, plan_cache_hit: bool = False,
                    trace_id: str | None = None) -> None:
        """Fingerprint + record in one call (mp-executor hot path)."""
        if not self._enabled:
            return
        self.record(self.fingerprint(text), latency_s, rows=rows,
                    error=error, plan_cache_hit=plan_cache_hit,
                    trace_id=trace_id)

    # --- snapshots ----------------------------------------------------------

    def snapshot(self) -> list[dict]:
        """Entries as dicts, hottest first."""
        with self._lock:
            shared_read(self, "_entries")
            entries = list(self._entries.values())
            out = []
            for e in sorted(entries, key=lambda e: -e.count):
                out.append({
                    "fingerprint": e.fingerprint,
                    "count": e.count,
                    "overcount_bound": e.overcount,
                    "errors": e.errors,
                    "plan_cache_hits": e.plan_cache_hits,
                    "rows_total": e.rows_total,
                    "latency_p50_ms": round(e.latency.quantile(0.5) * 1e3,
                                            3),
                    "latency_p99_ms": round(e.latency.quantile(0.99) * 1e3,
                                            3),
                    "trace_ids": list(e.trace_ids),
                    "first_seen": e.first_seen,
                    "last_seen": e.last_seen,
                })
            return out

    def rows(self) -> list[list]:
        """SHOW QUERY STATS rows (columns in QUERY_STATS_COLUMNS order)."""
        return [[s["fingerprint"], s["count"], s["errors"],
                 s["latency_p50_ms"], s["latency_p99_ms"],
                 s["rows_total"], s["plan_cache_hits"],
                 list(s["trace_ids"])]
                for s in self.snapshot()]


QUERY_STATS_COLUMNS = ["fingerprint", "count", "errors", "latency_p50_ms",
                       "latency_p99_ms", "rows_total", "plan_cache_hits",
                       "trace_ids"]

global_query_stats = QueryStatsRegistry()


# --------------------------------------------------------------------------
# device-stage attribution
# --------------------------------------------------------------------------

_stage_tls = threading.local()


class StageAccumulator:
    """Where the device seconds of one extent went, by stage.

    Single-thread by construction (thread-local activation); the kernel
    server ships a snapshot across the socket and the client merges it
    into its own active accumulator.
    """

    __slots__ = ("stages",)

    def __init__(self) -> None:
        self.stages: dict[str, dict] = {}

    def add(self, stage: str, seconds: float, count: int = 1) -> None:
        slot = self.stages.get(stage)
        if slot is None:
            slot = self.stages[stage] = {"seconds": 0.0, "count": 0}
        slot["seconds"] += float(seconds)
        slot["count"] += int(count)

    def merge(self, stages: dict | None) -> None:
        for name, slot in (stages or {}).items():
            self.add(name, slot.get("seconds", 0.0),
                     slot.get("count", 0) or 1)

    def snapshot(self) -> dict:
        return {name: dict(slot) for name, slot in self.stages.items()}


class _StageScope:
    __slots__ = ("_acc", "_prev")

    def __init__(self, acc: StageAccumulator) -> None:
        self._acc = acc
        self._prev = None

    def __enter__(self) -> StageAccumulator:
        self._prev = getattr(_stage_tls, "acc", None)
        _stage_tls.acc = self._acc
        return self._acc

    def __exit__(self, exc_type, exc, tb):
        _stage_tls.acc = self._prev
        return False


def collecting_stages(acc: StageAccumulator | None = None) -> _StageScope:
    """Activate a stage accumulator for the extent (context manager)."""
    return _StageScope(acc if acc is not None else StageAccumulator())


def stages_active() -> bool:
    """True when a stage accumulator is collecting on this thread (a
    PROFILE-d / accounted extent). Result caches use this to demote a
    verbatim hit to a warm seed: a profiled CALL exists to measure the
    device path, so serving stored bytes — attributing nothing — would
    defeat the run's purpose."""
    return getattr(_stage_tls, "acc", None) is not None


def record_stage(stage: str, seconds: float, count: int = 1) -> None:
    """Attribute device seconds to the ACTIVE accumulator, if any.

    Disarmed (no profiled/accounted extent running on this thread) this
    is one thread-local read — safe to call from every hot path.
    """
    acc = getattr(_stage_tls, "acc", None)
    if acc is not None:
        acc.add(stage, seconds, count)


def merge_stages(stages: dict | None) -> None:
    """Merge a remote snapshot (kernel-server reply) into the active
    accumulator, if any."""
    if not stages:
        return
    acc = getattr(_stage_tls, "acc", None)
    if acc is not None:
        acc.merge(stages)


def current_stages() -> StageAccumulator | None:
    return getattr(_stage_tls, "acc", None)


# --------------------------------------------------------------------------
# saturation / readiness plane
# --------------------------------------------------------------------------

#: counters whose MOVEMENT between evaluations marks saturation (the
#: USE "errors" axis); gauges are compared against thresholds directly
_RATE_SIGNALS = (
    # (snapshot key prefix/name, reason id)
    ("kernel_server.dispatch.shed_total", "kernel_server_shed"),
    ("kernel_server.admission_rejected_total", "kernel_server_shed"),
    ("kernel_server.daemon.dispatch.shed_total", "kernel_server_shed"),
    ("kernel_server.daemon.admission_rejected_total", "kernel_server_shed"),
)


class SaturationPlane:
    """Folds resource gauges + error counters into one readiness verdict.

    Stateful ON PURPOSE: error-class signals (sheds) are judged by
    movement since the previous evaluation — a single shed ages out of
    the verdict once the pressure stops, exactly like a rate() alarm.
    """

    def __init__(self) -> None:
        self._lock = tracked_lock("SaturationPlane._lock")
        self._last_counters: dict[str, float] = {}
        self._primed = False
        shared_field(self, "_last_counters")
        self.max_replica_lag = float(_env_int(ENV_MAX_LAG, 1000))
        self.max_fsync_backlog = float(_env_int(ENV_MAX_BACKLOG, 64 << 20))
        # trip BEFORE the serving plane's own hard shed threshold
        # (MEMGRAPH_TPU_PPR_MAX_QUEUE, default 256): load balancers see
        # the 503 while the queue is still servable
        self.max_ppr_queue = float(_env_int(ENV_MAX_PPR_QUEUE, 192))
        # per-shard dispatch is serial (shard-per-process): a deep
        # queue on ONE shard means a hot key / skewed hash range, and
        # admission control should shed before latency collapses
        self.max_shard_queue = float(_env_int(ENV_MAX_SHARD_QUEUE, 16))
        # streaming ingestion: source backlog (bytes behind the file
        # tail / records behind the broker) — /health must flip before
        # the consumer falls unboundedly behind the producers
        self.max_stream_lag = float(_env_int(ENV_MAX_STREAM_LAG, 100_000))

    def evaluate(self, ictx=None) -> dict:
        """One readiness verdict from the current metrics snapshot.

        Machine-readable: every reason carries {check, reason, value,
        threshold} so admission control can branch without parsing
        prose. ``ready`` is the conjunction of every check.
        """
        snap = {name: value for name, _kind, value
                in global_metrics.snapshot()}
        reasons: list[dict] = []
        checks: dict[str, str] = {}

        def trip(check: str, reason: str, value, threshold) -> None:
            checks[check] = "saturated"
            reasons.append({"check": check, "reason": reason,
                            "value": value, "threshold": threshold})

        def ok(check: str) -> None:
            checks.setdefault(check, "ok")

        # bolt session pool (gauges exported by BoltServer)
        live = snap.get("bolt.sessions_live")
        cap = snap.get("bolt.sessions_max") or 0
        if cap and live is not None and live >= cap:
            trip("bolt_sessions", "session pool exhausted", live, cap)
        else:
            ok("bolt_sessions")

        # mp executor in-flight vs worker count
        inflight = snap.get("mp_executor.in_flight")
        workers = snap.get("mp_executor.workers") or 0
        if workers and inflight is not None and inflight >= workers:
            trip("mp_executor", "all read workers busy", inflight, workers)
        else:
            ok("mp_executor")

        # kernel server: wedged daemon is an immediate not-ready
        if snap.get("kernel_server.daemon.wedged"):
            trip("kernel_server", "daemon wedged (overdue dispatch)",
                 1, 0)
        else:
            ok("kernel_server")

        # kernel server: sheds since the previous evaluation. The FIRST
        # evaluation only baselines — history predating the plane must
        # not read as fresh pressure.
        with self._lock:
            shared_write(self, "_last_counters")
            shed_now = 0.0
            for key, _reason in _RATE_SIGNALS:
                shed_now += float(snap.get(key) or 0.0)
            shed_prev = shed_now if not self._primed \
                else self._last_counters.get("shed", 0.0)
            self._last_counters["shed"] = shed_now
            self._primed = True
        if shed_now > shed_prev:
            trip("kernel_server_admission",
                 "requests shed since last evaluation (HBM pressure)",
                 shed_now - shed_prev, 0)
        else:
            ok("kernel_server_admission")

        # PPR serving plane: coalescing queue depth (local gauge, or the
        # daemon's own mirrored through the supervisor's health loop)
        depth = max(float(snap.get("ppr.queue_depth") or 0.0),
                    float(snap.get(
                        "kernel_server.daemon.ppr.queue_depth") or 0.0))
        if depth > self.max_ppr_queue:
            trip("ppr_queue", "PPR coalescing queue depth over budget",
                 depth, self.max_ppr_queue)
        else:
            ok("ppr_queue")

        # PPR batch-window occupancy: every window is leaving FULL and
        # requests still queue behind — the batcher is the bottleneck
        occ = max(float(snap.get("ppr.window_occupancy") or 0.0),
                  float(snap.get(
                      "kernel_server.daemon.ppr.window_occupancy")
                      or 0.0))
        if occ >= 1.0 and depth > 0:
            trip("ppr_window",
                 "PPR batch windows saturated with queue backlog",
                 occ, 1.0)
        else:
            ok("ppr_window")

        # sharded OLTP plane: per-shard queue depth (one gauge per
        # shard; serial per-shard dispatch makes depth the direct
        # saturation signal for a hot hash range)
        worst_shard = None
        for name, value in snap.items():
            if name.startswith("shard.queue_depth."):
                if worst_shard is None or value > worst_shard[1]:
                    worst_shard = (name, value)
        if worst_shard is not None and \
                worst_shard[1] > self.max_shard_queue:
            trip("shard_queue",
                 f"shard {worst_shard[0].rsplit('.', 1)[1]} queue "
                 "depth over budget", worst_shard[1],
                 self.max_shard_queue)
        else:
            ok("shard_queue")

        # replication lag (one gauge per replica)
        worst = None
        for name, value in snap.items():
            if name.startswith("replication.replica_lag."):
                if worst is None or value > worst[1]:
                    worst = (name, value)
        if worst is not None and worst[1] > self.max_replica_lag:
            trip("replication_lag",
                 f"replica {worst[0].rsplit('.', 1)[1]} lag over budget",
                 worst[1], self.max_replica_lag)
        else:
            ok("replication_lag")

        # WAL fsync backlog (batched-fsync deployments)
        backlog = snap.get("wal.fsync_backlog_bytes")
        if backlog is not None and backlog > self.max_fsync_backlog:
            trip("wal_fsync_backlog", "unfsynced WAL bytes over budget",
                 backlog, self.max_fsync_backlog)
        else:
            ok("wal_fsync_backlog")

        # streaming ingestion lag (one gauge per stream): the consumer
        # is falling behind its source faster than batches commit —
        # flip /health before the backlog grows without bound
        worst_stream = None
        for name, value in snap.items():
            if name.startswith("stream.lag."):
                if worst_stream is None or value > worst_stream[1]:
                    worst_stream = (name, value)
        if worst_stream is not None and \
                worst_stream[1] > self.max_stream_lag:
            trip("stream_lag",
                 f"stream {worst_stream[0].rsplit('.', 1)[1]} source "
                 "backlog over budget", worst_stream[1],
                 self.max_stream_lag)
        else:
            ok("stream_lag")

        ready = not reasons
        global_metrics.set_gauge("health.ready", 1.0 if ready else 0.0)
        if not ready:
            global_metrics.increment("health.not_ready_total")
        return {"ready": ready, "reasons": reasons, "checks": checks}

    def ingest_pressure(self) -> str | None:
        """Downstream-pressure probe for stream consumers: the check name
        that says polling MORE data would amplify overload, or None.

        Deliberately stateless (gauge thresholds only, no rate priming):
        the consumer loop calls this far more often than /health calls
        evaluate(), and must not perturb the shed-movement windows.
        """
        snap = {name: value for name, _kind, value
                in global_metrics.snapshot()}
        for name, value in snap.items():
            if name.startswith("replication.replica_lag.") and \
                    value > self.max_replica_lag:
                return "replication_lag"
        backlog = snap.get("wal.fsync_backlog_bytes")
        if backlog is not None and backlog > self.max_fsync_backlog:
            return "wal_fsync_backlog"
        if snap.get("kernel_server.daemon.wedged"):
            # the resident analytics plane (mgdelta warm refresh) is not
            # keeping up — pausing ingest is the graceful degradation
            return "kernel_server"
        return None


global_saturation = SaturationPlane()


# --------------------------------------------------------------------------
# exposition federation
# --------------------------------------------------------------------------

_SAMPLE_LINE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?( .*)$")


def label_exposition(text: str, instance: str) -> list[tuple]:
    """Parse one prometheus_text() payload into
    [(metric, type|None, labeled_sample_line)] with an ``instance``
    label injected into every sample (exemplar suffixes preserved)."""
    out: list[tuple] = []
    types: dict[str, str] = {}
    inst = instance.replace("\\", "\\\\").replace('"', '\\"')
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# TYPE "):
            parts = line.split()
            if len(parts) >= 4:
                types[parts[2]] = parts[3]
            continue
        if line.startswith("#"):
            continue
        m = _SAMPLE_LINE.match(line)
        if m is None:
            continue
        name, labels, rest = m.group(1), m.group(2), m.group(3)
        if labels:
            merged = '{instance="%s",%s' % (inst, labels[1:])
        else:
            merged = '{instance="%s"}' % inst
        family = name
        for suffix in ("_bucket", "_count", "_sum"):
            if name.endswith(suffix) and name[:-len(suffix)] in types:
                family = name[:-len(suffix)]
                break
        out.append((name, types.get(family), f"{name}{merged}{rest}"))
    return out


def federate_expositions(parts: dict[str, str]) -> str:
    """Merge several instances' expositions into ONE labeled payload.

    ``parts`` maps instance label -> prometheus_text() output. Every
    sample gains an ``instance`` label; one ``# TYPE`` line is emitted
    per metric family (first declaration wins)."""
    by_metric: dict[str, list[str]] = {}
    types: dict[str, str] = {}
    for instance in sorted(parts):
        for name, mtype, line in label_exposition(parts[instance],
                                                  instance):
            if mtype and name not in types:
                types[name] = mtype
            by_metric.setdefault(name, []).append(line)
    lines: list[str] = []
    emitted_types: set[str] = set()
    for name in sorted(by_metric):
        mtype = types.get(name)
        if mtype and name not in emitted_types:
            lines.append(f"# TYPE {name} {mtype}")
            emitted_types.add(name)
        lines.extend(by_metric[name])
    return "\n".join(lines) + ("\n" if lines else "")


def counters_exposition(counters: dict, extra_gauges: dict | None = None
                        ) -> str:
    """Render a flat counter dict (a kernel daemon's health-reply
    ``counters``) as a minimal exposition, so the daemon can appear as
    its own instance in the federated view."""
    from .metrics import _promname
    lines = []
    merged = dict(counters or {})
    merged.update(extra_gauges or {})
    for name in sorted(merged):
        metric = _promname(name)
        lines.append(f"# TYPE {metric} gauge")
        lines.append(f"{metric} {float(merged[name])}")
    return "\n".join(lines) + ("\n" if lines else "")
