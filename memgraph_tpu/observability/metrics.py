"""Counters / gauges / histograms registry.

Counterpart of the reference's metrics layer
(/root/reference/src/metrics/prometheus_metrics.hpp): named counters with
types, snapshot for SHOW METRICS INFO, Prometheus text exposition for the
monitoring endpoint.
"""

from __future__ import annotations

import threading
from collections import defaultdict

from ..utils.locks import tracked_lock
from ..utils.sanitize import shared_field, shared_read, shared_write


def _promname(name: str) -> str:
    return name.replace(".", "_").replace("-", "_")


class Metrics:
    def __init__(self) -> None:
        self._lock = tracked_lock("Metrics._lock")
        self._counters: dict[str, int] = defaultdict(int)
        self._gauges: dict[str, float] = {}
        self._histograms: dict[str, list] = defaultdict(list)
        # cumulative count/sum survive quantile-window trimming: summary
        # _count/_sum must be monotonic or rate() queries see resets
        self._hist_count: dict[str, int] = defaultdict(int)
        self._hist_sum: dict[str, float] = defaultdict(float)
        shared_field(self, "_counters", "_gauges", "_histograms",
                     "_hist_count", "_hist_sum")

    def increment(self, name: str, delta: int = 1) -> None:
        with self._lock:
            shared_write(self, "_counters")
            self._counters[name] += delta

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            shared_write(self, "_gauges")
            self._gauges[name] = value

    def observe(self, name: str, value: float) -> None:
        with self._lock:
            shared_write(self, "_histograms")
            h = self._histograms[name]
            h.append(value)
            self._hist_count[name] += 1
            self._hist_sum[name] += value
            if len(h) > 10_000:
                del h[: len(h) // 2]

    def snapshot(self) -> list[tuple[str, str, float]]:
        with self._lock:
            shared_read(self, "_counters")
            out = [(n, "Counter", float(v))
                   for n, v in sorted(self._counters.items())]
            out += [(n, "Gauge", float(v))
                    for n, v in sorted(self._gauges.items())]
            for n, values in sorted(self._histograms.items()):
                if not values:
                    continue
                s = sorted(values)
                for q, suffix in ((0.5, "p50"), (0.9, "p90"), (0.99, "p99")):
                    idx = min(int(q * len(s)), len(s) - 1)
                    out.append((f"{n}_{suffix}", "Histogram", float(s[idx])))
            return out

    def prometheus_text(self) -> str:
        lines = []
        with self._lock:
            shared_read(self, "_counters")
            counters = sorted(self._counters.items())
            gauges = sorted(self._gauges.items())
            histograms = {n: list(v)
                          for n, v in sorted(self._histograms.items())}
            hist_count = dict(self._hist_count)
            hist_sum = dict(self._hist_sum)
        for name, value in counters:
            metric = _promname(name)
            lines.append(f"# TYPE {metric} counter")
            lines.append(f"{metric} {float(value)}")
        for name, value in gauges:
            metric = _promname(name)
            lines.append(f"# TYPE {metric} gauge")
            lines.append(f"{metric} {float(value)}")
        # summary exposition: quantiles + _count + _sum (reference:
        # prometheus_metrics.hpp histogram family)
        for name, values in histograms.items():
            if not values:
                continue
            metric = _promname(name)
            s = sorted(values)
            lines.append(f"# TYPE {metric} summary")
            for q in (0.5, 0.9, 0.99):
                idx = min(int(q * len(s)), len(s) - 1)
                lines.append(f'{metric}{{quantile="{q}"}} {float(s[idx])}')
            lines.append(f"{metric}_count {hist_count.get(name, len(s))}")
            lines.append(
                f"{metric}_sum {float(hist_sum.get(name, sum(s)))}")
        return "\n".join(lines) + "\n"


global_metrics = Metrics()
