"""Counters / gauges / histograms registry.

Counterpart of the reference's metrics layer
(/root/reference/src/metrics/prometheus_metrics.hpp): named counters with
types, snapshot for SHOW METRICS INFO, Prometheus text exposition for the
monitoring endpoint.

r13 (mgtrace): ``observe()`` now records into a REAL histogram — fixed
exponential buckets with correct cumulative Prometheus exposition
(``_bucket{le=...}`` monotone, ``+Inf`` bucket == ``_count``) instead of
the windowed-summary approximation, so p50/p99 survive scrape-side
``histogram_quantile()`` and rate() math. Latency observations taken
inside an armed trace carry the trace id as an OpenMetrics exemplar, so
a p99 spike links straight to a retained trace in /traces.
"""

from __future__ import annotations

import bisect
import re
import time
from collections import defaultdict

from ..utils.locks import tracked_lock
from ..utils.sanitize import shared_field, shared_read, shared_write

_NAME_BAD = re.compile(r"[^a-zA-Z0-9_:]")


def _promname(name: str) -> str:
    """Prometheus metric-name sanitization: every invalid character maps
    to '_' and a leading digit gets a '_' prefix (names like
    "edge_count[Knows]" must not produce an unparseable exposition)."""
    out = _NAME_BAD.sub("_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def _promlabel(value: str) -> str:
    """Prometheus label-VALUE escaping (backslash, quote, newline)."""
    return str(value).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


#: fixed exponential bucket bounds (seconds): 100µs .. ~1677s, factor 2.
#: One shared layout for every histogram keeps exposition predictable
#: and cross-metric comparisons honest.
DEFAULT_BUCKETS = tuple(0.0001 * (2 ** i) for i in range(24))

#: Every metric name product code may emit through ``global_metrics``
#: (r14, mgstat). Entries ending in ``*`` declare a dynamic FAMILY whose
#: members share the literal prefix (``operator.*`` covers
#: ``operator.ScanAll`` etc.). mglint MG005 (stat-registry) statically
#: enforces that (a) every literal name passed to increment()/
#: set_gauge()/observe() appears here (or matches a family), (b) every
#: f-string name's literal prefix matches a declared family, (c) every
#: declared name/family has at least one live emit site, and (d) no
#: name is declared twice — a typo'd metric silently splits a series,
#: and a dead registration means dashboards "cover" a metric that can
#: never move.
STAT_NAMES = (
    # query engine
    "query.prepared",
    "query.finished",
    "query.execution_latency_sec",
    "operator.*",                  # per-operator completion counters
    "storage.*",                   # per-query write-stat counters
    "mgstat.evictions_total",      # space-saving top-K evictions
    # bolt session pool
    "bolt.prepare_latency_sec",
    "bolt.connections_rejected_total",
    "bolt.sessions_live",
    "bolt.sessions_max",
    # multiprocess read executor
    "mp_executor.in_flight",
    "mp_executor.workers",
    "mp_executor.errors_total",
    "mp_executor.worker_respawn_total",
    # sharded OLTP execution plane (r18, mgshard)
    "shard.requests_total",
    "shard.scatter_gather_total",
    "shard.stale_epoch_bounces_total",
    "shard.twopc_total",
    "shard.twopc_aborts_total",
    "shard.moves_total",
    "shard.move_duration_sec",
    "shard.map_epoch",              # routing-table fencing epoch gauge
    "shard.worker_respawn_total",
    "shard.write_in_doubt_total",   # writes surfaced as WriteInDoubtError
    "shard.ops.*",                  # per-shard routed-op counters
    "shard.op_latency_sec.*",       # per-shard latency histograms
    "shard.queue_depth.*",          # per-shard in-flight gauges
    # kernel server (local process + mirrored daemon state)
    "kernel_server.dispatch.*",    # typed per-outcome dispatch counters
    "kernel_server.daemon.*",      # daemon counters mirrored as gauges
    "kernel_server.admission_rejected_total",
    "kernel_server.dispatch_latency_sec",
    "kernel_server.in_flight",
    "kernel_server.hbm_budget_bytes",
    "kernel_server.hbm_modeled_peak_bytes",
    "kernel_server.supervisor.health_checks_total",
    "kernel_server.supervisor.wedge_detected_total",
    "kernel_server.supervisor.restarts_total",
    "kernel_server.client.retries_total",
    # PPR serving plane (r16): coalesced batched multi-source PPR
    "ppr.requests_total",
    "ppr.batches_total",
    "ppr.batch_size",              # histogram of executed batch widths
    "ppr.coalesced_total",         # requests that shared a batch
    "ppr.cache_hit_total",
    "ppr.cache_miss_total",
    "ppr.cache_invalidate_total",
    "ppr.warm_start_total",
    "ppr.shed_total",
    "ppr.queue_depth",             # coalescing queue backlog gauge
    "ppr.window_occupancy",        # last batch width / max width gauge
    # device compile plane (r17, mgxla): runtime witness for the static
    # compile budget — every XLA backend compile bumps it
    "jit.compile_total",
    # compiled Cypher read lane (r20, mglane)
    "lane.compiled_total",          # lane programs compiled (per shape)
    "lane.hit_total",               # queries served from a compiled lane
    "lane.fallback_total.*",        # typed per-reason loud fallbacks
    "lane.compile_latency_sec",     # histogram: per-program compile cost
    "lane.resident",                # resident compiled-programs gauge
    "lane.remote_dispatch_total",   # hop programs routed via kernel srv
    # incremental analytics plane (r19, mgdelta): commit-to-fresh-result
    "delta.applied_total",          # EdgeDelta splices applied
    "delta.compacted_total",        # bounded-accumulation full rebuilds
    "delta.fallback_rebuild_total",  # wrapped log / failed splice colds
    "delta.edge_count",             # histogram: edges per applied delta
    "delta.warm_start_total",
    "delta.cold_start_total",       # LOUD monotone-unsafe cold starts
    "delta.warm_start_iterations",  # histogram: iterations after warm
    "delta.resident_generations",   # resident graph generations gauge
    # out-of-core streamed tier (r21, mgtier)
    "tier.admission_*",             # resident/streamed/shed verdicts
    "tier.blocks_streamed_total",   # edge blocks shipped host→device
    "tier.bytes_streamed_total",    # int32+f32-equivalent volume swept
    "tier.compressed_bytes_total",  # wire bytes actually shipped
    "tier.blocks_repacked_total",   # delta-spliced rows re-encoded
    "tier.blocks_reused_total",     # rows the splice left untouched
    "tier.modeled_request_bytes",   # admission-estimator price of the run
    "tier.block_transfer_latency_sec",   # histogram: per-block H2D
    "tier.transfer_hidden_fraction",     # histogram: overlap efficiency
    # analytics / checkpoint plane
    "analytics.checkpoint.saved_total",
    "analytics.checkpoint.restored_total",
    "analytics.resume_total",
    "analytics.chunk_deadline_exceeded_total",
    "analytics.resumable_run_seconds",
    "analytics.device_fault.*",    # typed per-kind device-fault counters
    "analytics.kernel_routed_total",
    "analytics.kernel_route_fallback_total",
    # streaming ingestion plane (r17, mgstream): supervised exactly-once
    # consumers — transactional offsets, quarantine, backpressure
    "stream.batches_total",         # batches durably committed
    "stream.records_total",         # records durably committed
    "stream.batch_latency_sec",     # histogram: poll→commit per batch
    "stream.redeliveries_total",    # failed batches rolled back for retry
    "stream.dead_letter_total",     # poison batches quarantined
    "stream.reconnects_total",      # RetryPolicy-backed source reconnects
    "stream.poll_errors_total",     # source poll failures (pre-reconnect)
    "stream.ack_failures_total",    # post-commit consumer acks that failed
    "stream.pauses_total",          # backpressure pause transitions
    "stream.paused",                # gauge: 1 while polling is paused
    "stream.lag.*",                 # per-stream source-backlog gauges
    # triggers (fired on the committed delta)
    "trigger.fired_total",
    "trigger.errors_total",         # failing trigger statements (LOUD)
    # durability
    "wal.fsync_latency_sec",
    "wal.fsync_backlog_bytes",
    "wal.segments_rotated",
    "wal.recovery_truncations",
    # replication
    "replication.rpc_failures",
    "replication.ship_latency_sec",
    "replication.fenced_total",
    "replication.strict_sync_demotions",
    "replication.replica_lag.*",       # per-replica txn lag gauges
    "replication.replica_health.*",    # per-replica up/down gauges
    "replication.replica_degraded.*",  # per-replica STRICT_SYNC demotions
    # coordination
    "coordination.current_epoch",
    "coordination.failover_attempts",
    "coordination.failovers_total",
    "coordination.federation_scrapes_total",
    # saturation plane
    "health.ready",
    "health.not_ready_total",
    # exception-flow contracts (mgflow, r24): registry-shape gauges,
    # refreshed on every GET /stats read
    "mgflow.contract_roots",        # serving roots under contract
    "mgflow.escapes_total",         # escape types the contracts admit
)


class Histogram:
    """Fixed-bucket histogram with cumulative exposition + exemplars.

    Not thread-safe on its own — the owning :class:`Metrics` registry
    serializes access under its lock.
    """

    __slots__ = ("bounds", "bucket_counts", "count", "sum", "exemplars")

    def __init__(self, bounds=DEFAULT_BUCKETS) -> None:
        self.bounds = tuple(bounds)
        self.bucket_counts = [0] * (len(self.bounds) + 1)  # last = +Inf
        self.count = 0
        self.sum = 0.0
        #: bucket index -> (value, trace_id, unix_ts) — the latest
        #: traced observation landing in that bucket
        self.exemplars: dict[int, tuple[float, str, float]] = {}

    def observe(self, value: float, trace_id: str | None = None) -> None:
        idx = bisect.bisect_left(self.bounds, value)
        self.bucket_counts[idx] += 1
        self.count += 1
        self.sum += value
        if trace_id:
            self.exemplars[idx] = (value, trace_id, time.time())

    def quantile(self, q: float) -> float:
        """Estimate via linear interpolation inside the hit bucket (the
        same math PromQL's histogram_quantile applies)."""
        if self.count == 0:
            return 0.0
        rank = q * self.count
        seen = 0
        for i, c in enumerate(self.bucket_counts):
            if c == 0:
                continue
            if seen + c >= rank:
                lo = self.bounds[i - 1] if i > 0 else 0.0
                hi = self.bounds[i] if i < len(self.bounds) \
                    else self.bounds[-1] * 2
                frac = (rank - seen) / c
                return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
            seen += c
        return self.bounds[-1] * 2

    def cumulative(self):
        """[(le_bound_or_inf, cumulative_count)] — exposition order."""
        total = 0
        out = []
        for i, c in enumerate(self.bucket_counts):
            total += c
            bound = self.bounds[i] if i < len(self.bounds) else None
            out.append((bound, total))
        return out


class Metrics:
    def __init__(self) -> None:
        self._lock = tracked_lock("Metrics._lock")
        self._counters: dict[str, int] = defaultdict(int)
        self._gauges: dict[str, float] = {}
        self._histograms: dict[str, Histogram] = {}
        shared_field(self, "_counters", "_gauges", "_histograms")

    def increment(self, name: str, delta: int = 1) -> None:
        with self._lock:
            shared_write(self, "_counters")
            self._counters[name] += delta

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            shared_write(self, "_gauges")
            self._gauges[name] = value

    def observe(self, name: str, value: float,
                trace_id: str | None = None) -> None:
        if trace_id is None:
            # latency observed inside an armed trace links back to it
            # (exemplar); disarmed this is one attribute read
            from .trace import current_trace_id
            trace_id = current_trace_id()
        with self._lock:
            shared_write(self, "_histograms")
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms[name] = Histogram()
            h.observe(value, trace_id)

    def snapshot(self) -> list[tuple[str, str, float]]:
        with self._lock:
            shared_read(self, "_counters")
            out = [(n, "Counter", float(v))
                   for n, v in sorted(self._counters.items())]
            out += [(n, "Gauge", float(v))
                    for n, v in sorted(self._gauges.items())]
            for n, h in sorted(self._histograms.items()):
                if not h.count:
                    continue
                for q, suffix in ((0.5, "p50"), (0.9, "p90"), (0.99, "p99")):
                    out.append((f"{n}_{suffix}", "Histogram",
                                float(h.quantile(q))))
            return out

    def prometheus_text(self) -> str:
        lines = []
        with self._lock:
            shared_read(self, "_counters")
            counters = sorted(self._counters.items())
            gauges = sorted(self._gauges.items())
            histograms = [
                (n, h.cumulative(), h.count, h.sum, dict(h.exemplars),
                 h.bounds)
                for n, h in sorted(self._histograms.items())]
        for name, value in counters:
            metric = _promname(name)
            lines.append(f"# TYPE {metric} counter")
            lines.append(f"{metric} {float(value)}")
        for name, value in gauges:
            metric = _promname(name)
            lines.append(f"# TYPE {metric} gauge")
            lines.append(f"{metric} {float(value)}")
        # cumulative histogram exposition (reference:
        # prometheus_metrics.hpp histogram family): every bucket line is
        # the count of observations ≤ le, the +Inf bucket equals _count,
        # and traced observations append OpenMetrics exemplars
        for name, cumulative, count, total, exemplars, bounds in histograms:
            if not count:
                continue
            metric = _promname(name)
            lines.append(f"# TYPE {metric} histogram")
            for i, (bound, cum) in enumerate(cumulative):
                le = "+Inf" if bound is None else repr(float(bound))
                line = f'{metric}_bucket{{le="{le}"}} {cum}'
                ex = exemplars.get(i)
                if ex is not None:
                    value, trace_id, ts = ex
                    line += (f' # {{trace_id="{_promlabel(trace_id)}"}}'
                             f" {float(value)} {ts:.3f}")
                lines.append(line)
            lines.append(f"{metric}_count {count}")
            lines.append(f"{metric}_sum {float(total)}")
        return "\n".join(lines) + "\n"


global_metrics = Metrics()
