"""Counters / gauges / histograms registry.

Counterpart of the reference's metrics layer
(/root/reference/src/metrics/prometheus_metrics.hpp): named counters with
types, snapshot for SHOW METRICS INFO, Prometheus text exposition for the
monitoring endpoint.
"""

from __future__ import annotations

import threading
from collections import defaultdict


class Metrics:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: dict[str, int] = defaultdict(int)
        self._gauges: dict[str, float] = {}
        self._histograms: dict[str, list] = defaultdict(list)

    def increment(self, name: str, delta: int = 1) -> None:
        with self._lock:
            self._counters[name] += delta

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value

    def observe(self, name: str, value: float) -> None:
        with self._lock:
            h = self._histograms[name]
            h.append(value)
            if len(h) > 10_000:
                del h[: len(h) // 2]

    def snapshot(self) -> list[tuple[str, str, float]]:
        with self._lock:
            out = [(n, "Counter", float(v))
                   for n, v in sorted(self._counters.items())]
            out += [(n, "Gauge", float(v))
                    for n, v in sorted(self._gauges.items())]
            for n, values in sorted(self._histograms.items()):
                if not values:
                    continue
                s = sorted(values)
                for q, suffix in ((0.5, "p50"), (0.9, "p90"), (0.99, "p99")):
                    idx = min(int(q * len(s)), len(s) - 1)
                    out.append((f"{n}_{suffix}", "Histogram", float(s[idx])))
            return out

    def prometheus_text(self) -> str:
        lines = []
        for name, kind, value in self.snapshot():
            metric = name.replace(".", "_").replace("-", "_")
            lines.append(f"# TYPE {metric} "
                         f"{'counter' if kind == 'Counter' else 'gauge'}")
            lines.append(f"{metric} {value}")
        return "\n".join(lines) + "\n"


global_metrics = Metrics()
