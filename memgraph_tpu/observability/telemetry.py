"""Anonymous usage telemetry (reference: src/telemetry/telemetry.cpp —
periodic phone-home with pluggable collectors, gated by the
--telemetry-enabled flag, off by default here).

A stable anonymous run id lives in the kvstore; each beat POSTs a JSON
document assembled from registered collectors. Delivery failures are
swallowed (the reference buffers and retries; we keep the last error for
observability instead — this environment has no egress anyway).
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request
import uuid

DEFAULT_INTERVAL_SEC = 8 * 3600   # reference: every 8h (memgraph.cpp:1006)
_RUN_ID_KEY = "telemetry:run_id"


class Telemetry:
    def __init__(self, endpoint: str, kvstore=None,
                 interval_sec: float = DEFAULT_INTERVAL_SEC,
                 first_beat_sec: float = None) -> None:
        import os
        if first_beat_sec is None:
            first_beat_sec = float(os.environ.get(
                "MEMGRAPH_TPU_TELEMETRY_FIRST_BEAT_SEC", "60"))
        self.endpoint = endpoint
        self.interval_sec = interval_sec
        self.first_beat_sec = first_beat_sec
        self.run_id = self._load_run_id(kvstore)
        self.started_at = time.time()
        # beat bookkeeping is written by the telemetry thread and read by
        # status collectors / tests on other threads — guarded state
        from ..utils.locks import tracked_lock
        from ..utils.sanitize import shared_field
        self._stats_lock = tracked_lock("Telemetry._stats_lock")
        self.beats_sent = 0
        self.last_error: str | None = None
        shared_field(self, "beats_sent", "last_error")
        self._collectors: dict[str, callable] = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.add_collector("uptime", lambda: time.time() - self.started_at)
        self.add_collector("version", self._version)

    @staticmethod
    def _version():
        from .. import __version__
        return __version__

    @staticmethod
    def _load_run_id(kvstore) -> str:
        if kvstore is None:
            return str(uuid.uuid4())
        existing = kvstore.get_str(_RUN_ID_KEY)
        if existing:
            return existing
        run_id = str(uuid.uuid4())
        kvstore.put(_RUN_ID_KEY, run_id)
        return run_id

    def add_collector(self, name: str, fn) -> None:
        """fn() -> JSON-serializable value; exceptions are isolated per
        collector so one broken probe never kills the beat."""
        self._collectors[name] = fn

    def collect(self) -> dict:
        data = {}
        for name, fn in self._collectors.items():
            try:
                data[name] = fn()
            except Exception as e:
                data[name] = f"<collector error: {e}>"
        return {"run_id": self.run_id, "timestamp": time.time(),
                "data": data}

    def send_beat(self) -> bool:
        payload = json.dumps(self.collect()).encode()
        req = urllib.request.Request(
            self.endpoint, data=payload,
            headers={"Content-Type": "application/json"})
        from ..utils.sanitize import shared_write
        try:
            with urllib.request.urlopen(req, timeout=10) as resp:
                resp.read()
            with self._stats_lock:
                shared_write(self, "beats_sent")
                self.beats_sent += 1
                self.last_error = None
            return True
        except Exception as e:
            with self._stats_lock:
                shared_write(self, "last_error")
                self.last_error = str(e)
            return False

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="telemetry")
        self._thread.start()

    def _loop(self) -> None:
        if self._stop.wait(self.first_beat_sec):
            return
        while not self._stop.is_set():
            self.send_beat()
            if self._stop.wait(self.interval_sec):
                return

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


def attach_storage_collectors(telemetry: Telemetry, ctx) -> None:
    """The reference's database collector: object counts only — never
    query text or data (telemetry/collectors.cpp). `ctx` may be an
    InterpreterContext (read live — STORAGE MODE switches replace the
    storage object) or a bare storage."""
    def counts():
        storage = getattr(ctx, "storage", ctx)
        info = storage.info()   # public surface shared with SHOW STORAGE INFO
        return {"vertices": info["vertex_count"],
                "edges": info["edge_count"]}
    telemetry.add_collector("storage", counts)


def attach_query_collectors(telemetry: Telemetry) -> None:
    from .metrics import global_metrics

    def counters():
        return {name: value
                for name, kind, value in global_metrics.snapshot()
                if name.startswith("query.")}
    telemetry.add_collector("query_counters", counters)
