"""Audit log: buffered JSONL of executed queries.

Counterpart of the reference's audit log (/root/reference/src/audit/log.hpp
— buffered (user, query, params) records with logrotate reopen on SIGUSR2).
"""

from __future__ import annotations

import json
import os
import signal
import threading
import time


class AuditLog:
    def __init__(self, path: str, buffer_size: int = 100,
                 install_sigusr2: bool = False):
        self.path = path
        self.buffer_size = buffer_size
        self._lock = threading.Lock()
        self._buffer: list[str] = []
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self._file = open(path, "a", encoding="utf-8")
        if install_sigusr2:
            signal.signal(signal.SIGUSR2, self._reopen_handler)

    def record(self, username: str, query: str, parameters=None) -> None:
        entry = json.dumps({
            "timestamp": time.time(),
            "address": "",
            "username": username or "",
            "query": query,
            "params": parameters or {},
        })
        with self._lock:
            self._buffer.append(entry)
            if len(self._buffer) >= self.buffer_size:
                self._flush_locked()

    def flush(self) -> None:
        with self._lock:
            self._flush_locked()

    def _flush_locked(self) -> None:
        if self._buffer:
            self._file.write("\n".join(self._buffer) + "\n")
            self._file.flush()
            self._buffer.clear()

    def _reopen_handler(self, signum, frame) -> None:
        """SIGUSR2: reopen after logrotate (reference: memgraph.cpp:495)."""
        with self._lock:
            self._flush_locked()
            self._file.close()
            self._file = open(self.path, "a", encoding="utf-8")

    def close(self) -> None:
        self.flush()
        self._file.close()


class SessionTrace:
    """Per-session event timeline (reference: SESSION TRACE ON,
    interpreter.cpp:8530 EmitSessionTraceEvent)."""

    def __init__(self) -> None:
        self.enabled = False
        self.events: list[dict] = []

    def emit(self, event: str, **data) -> None:
        if self.enabled:
            self.events.append({"ts": time.time(), "event": event, **data})

    def drain(self) -> list[dict]:
        out = self.events
        self.events = []
        return out
