"""mgtrace: low-overhead, always-compiled-in query tracing.

One Cypher query yields ONE connected trace — session → parse → plan →
execute → storage txn (MVCC begin/commit) → kernel-server dispatch →
device stages → replication acks — across every process boundary the
deployment has: Bolt frames (``extra`` metadata field), the
kernel-server request protocol, ``mp_executor`` job envelopes, and the
replication/raft wire.

Design rules:

* **Disarmed costs ~nothing.** Tracing is compiled in everywhere but
  armed only via ``MEMGRAPH_TPU_TRACE=1`` (or programmatically,
  ``enable()``). Every public entry point starts with one attribute
  read; disarmed, ``span()`` returns a shared no-op context manager and
  ``inject()``/``activate()``/``begin_trace()`` return ``None``/no-ops.
  The overhead-guard test (tests/test_mgtrace.py) enforces the ≤2%
  budget on a tier-1 micro-benchmark.

* **Spans open only through this module's context-manager API** —
  ``span()`` for synchronous extents, ``record_span()`` for atomic
  after-the-fact records (phases whose start/end straddle generator
  boundaries), ``begin_trace()`` for the one sanctioned long-lived root
  per query (finished in exactly one place by its owner). The raw
  ``_begin_span``/``_end_span`` primitives are private to this file;
  mglint's MG005 span-registry check rejects product code that touches
  them, and requires every literal span name to be declared in
  :data:`SPAN_NAMES`.

* **Head-based sampling, slow/error always kept.** The keep/drop
  decision is taken once, at the trace root, from a deterministic hash
  of the trace id against ``MEMGRAPH_TPU_TRACE_SAMPLE`` — and travels
  in the carrier so every process agrees. Regardless of the sample
  verdict, a trace whose root ran ≥ ``MEMGRAPH_TPU_TRACE_SLOW_MS`` or
  that contains an errored span is retained.

* **Cross-process spans ship home.** A kernel-server dispatch or
  mp_executor worker records its spans locally under the propagated
  trace id, then ``take_trace()`` pops them into the reply envelope and
  the caller ``adopt_spans()``-s them — so the retained trace in the
  querying process is the whole connected picture, not a stub.

Exports: ``traces_json()`` (the /traces endpoint), ``to_jsonl()``, and
``chrome_trace()`` — Chrome trace-event JSON loadable in Perfetto /
chrome://tracing. ``MEMGRAPH_TPU_TRACE_XLA=1`` additionally bridges
every span through ``jax.profiler.TraceAnnotation`` so spans appear
inside XLA device profiles.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time

log = logging.getLogger(__name__)

ENV_ARM = "MEMGRAPH_TPU_TRACE"
ENV_SAMPLE = "MEMGRAPH_TPU_TRACE_SAMPLE"
ENV_SLOW_MS = "MEMGRAPH_TPU_TRACE_SLOW_MS"
ENV_RING = "MEMGRAPH_TPU_TRACE_RING"
ENV_XLA = "MEMGRAPH_TPU_TRACE_XLA"

#: Every span name product code may open. mglint MG005 (span-registry)
#: statically enforces that (a) every literal name passed to span()/
#: record_span()/begin_trace() in memgraph_tpu/ appears here, and
#: (b) every name here has at least one live open site — a dead
#: registration means dashboards "cover" a span that can never fire.
SPAN_NAMES = (
    "bolt.run",            # one Bolt RUN..PULL* exchange (session root)
    "query",               # interpreter root: prepare -> summary
    "query.parse",         # text -> AST (cache-aware)
    "query.plan",          # AST -> operator tree (cache-aware)
    "query.execute",       # stream drain: first pull -> exhaustion
    "query.commit",        # autocommit finalization (interpreter side)
    "mvcc.begin",          # storage transaction begin
    "mvcc.commit",         # storage engine commit (durability + repl)
    "kernel.request",      # client->kernel-server round trip
    "kernel.dispatch",     # server-side supervised dispatch
    "device.transfer",     # partition-centric blocking + device_put
    "device.chunk",        # one compiled chunk of device iterations
    "mp.execute",          # parent->mp-worker round trip
    "mp.worker",           # worker-side prepare+pull
    "shard.request",       # router->shard-owner round trip (r18)
    "shard.worker",        # shard-worker-side statement execution
    "repl.ship",           # one WAL frame ship + ack, per replica
    "repl.apply",          # replica-side system-txn application
    "raft.rpc",            # outbound raft RPC (request + response)
    "raft.handle",         # inbound raft RPC application
)

_SPAN_NAME_SET = frozenset(SPAN_NAMES)


def _env_flag(name: str) -> bool:
    return os.environ.get(name, "") not in ("", "0")


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _new_id(nbytes: int = 8) -> str:
    return os.urandom(nbytes).hex()


def _sample_decision(trace_id: str, rate: float) -> bool:
    """Deterministic head-sampling verdict from the trace id: every
    process that sees the id would agree even without the carrier."""
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    return int(trace_id[:8], 16) / 0xFFFFFFFF < rate


class TraceContext:
    """The propagated identity: (trace_id, span_id, sampled)."""

    __slots__ = ("trace_id", "span_id", "sampled")

    def __init__(self, trace_id: str, span_id: str, sampled: bool) -> None:
        self.trace_id = trace_id
        self.span_id = span_id
        self.sampled = sampled

    def carrier(self) -> dict:
        return {"trace_id": self.trace_id, "span_id": self.span_id,
                "sampled": self.sampled}


class _NoopSpan:
    """Shared disarmed-path context manager: one allocation per process."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False

    def __bool__(self):
        return False

    def set(self, **attrs) -> None:
        pass


_NOOP = _NoopSpan()


class _NullActivation:
    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, exc_type, exc, tb):
        return False


_NULL_ACTIVATION = _NullActivation()


def _clean_attrs(attrs: dict) -> dict:
    """Attrs must survive JSON serialization across process boundaries."""
    out = {}
    for k, v in attrs.items():
        if v is None or isinstance(v, (str, int, float, bool)):
            out[k] = v
        else:
            out[k] = str(v)
    return out


class _LiveSpan:
    """An open span; created only while armed, via span()."""

    __slots__ = ("_tracer", "name", "trace_id", "span_id", "parent_id",
                 "_t0_wall", "_t0_perf", "attrs", "status", "error",
                 "_prev_ctx", "_xla")

    def __init__(self, tracer: "Tracer", name: str, ctx_parent, attrs):
        self._tracer = tracer
        self.name = name
        if ctx_parent is not None:
            self.trace_id = ctx_parent.trace_id
            self.parent_id = ctx_parent.span_id
            sampled = ctx_parent.sampled
        else:
            self.trace_id = _new_id(16)
            self.parent_id = None
            sampled = _sample_decision(self.trace_id, tracer.sample_rate)
        self.span_id = _new_id()
        self.attrs = _clean_attrs(attrs) if attrs else {}
        self.status = "ok"
        self.error = None
        self._prev_ctx = None
        self._xla = None
        self._t0_wall = time.time()
        self._t0_perf = time.perf_counter()
        # children opened inside this extent hang off this span
        self._prev_ctx = tracer._swap_current(
            TraceContext(self.trace_id, self.span_id, sampled))
        if tracer.xla_bridge:
            self._xla = tracer._enter_xla(name)

    def __bool__(self):
        return True

    def set(self, **attrs) -> None:
        self.attrs.update(_clean_attrs(attrs))

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            self.status = "error"
            self.error = f"{exc_type.__name__}: {exc}"
        t = self._tracer
        if self._xla is not None:
            t._exit_xla(self._xla)
        dur = time.perf_counter() - self._t0_perf
        t._swap_current(self._prev_ctx)
        t._record(self.trace_id, {
            "trace_id": self.trace_id, "span_id": self.span_id,
            "parent_id": self.parent_id, "name": self.name,
            "ts": self._t0_wall, "dur_s": dur, "status": self.status,
            "error": self.error, "attrs": self.attrs,
            "pid": os.getpid(), "tid": threading.get_ident()})
        return False


class _Activation:
    __slots__ = ("_tracer", "_ctx", "_prev")

    def __init__(self, tracer: "Tracer", ctx: TraceContext) -> None:
        self._tracer = tracer
        self._ctx = ctx
        self._prev = None

    def __enter__(self):
        self._prev = self._tracer._swap_current(self._ctx)
        return self._ctx

    def __exit__(self, exc_type, exc, tb):
        self._tracer._swap_current(self._prev)
        return False


class _Adoption(_Activation):
    """Activation of a REMOTE parent context; with retain=True the trace
    is finalized locally on scope exit (for one-way hops whose spans
    cannot ship back — raft/replication appliers)."""

    __slots__ = ("_retain",)

    def __init__(self, tracer, ctx, retain: bool) -> None:
        super().__init__(tracer, ctx)
        self._retain = retain

    def __exit__(self, exc_type, exc, tb):
        super().__exit__(exc_type, exc, tb)
        if self._retain:
            self._tracer._finalize(self._ctx.trace_id, self._ctx.sampled,
                                   root_dur_s=None)
        return False


class TraceHandle:
    """The one sanctioned long-lived root span (a query's lifetime spans
    multiple protocol messages, so its root cannot be a ``with`` block).
    Mint with begin_trace(); the owner calls finish() exactly once."""

    __slots__ = ("_tracer", "name", "ctx", "parent_id", "t0_wall",
                 "t0_perf", "_done", "_owns_finalize")

    def __init__(self, tracer: "Tracer", name: str, ctx: TraceContext,
                 parent_id: str | None, owns_finalize: bool) -> None:
        self._tracer = tracer
        self.name = name
        self.ctx = ctx
        self.parent_id = parent_id
        self.t0_wall = time.time()
        self.t0_perf = time.perf_counter()
        self._done = False
        # finalization ownership: only the OUTERMOST local handle (a
        # true root, or the process-edge adopter of an external
        # client's carrier) moves the trace to the retained ring — an
        # inner handle (the interpreter's "query" under a Bolt session,
        # or inside an mp/kernel worker whose spans ship home via
        # take_trace) must leave the buffer alone
        self._owns_finalize = owns_finalize

    @property
    def trace_id(self) -> str:
        return self.ctx.trace_id

    def finish(self, status: str = "ok", error: str | None = None,
               force_keep: bool = False, **attrs) -> None:
        if self._done:
            return
        self._done = True
        dur = time.perf_counter() - self.t0_perf
        t = self._tracer
        t._record(self.ctx.trace_id, {
            "trace_id": self.ctx.trace_id, "span_id": self.ctx.span_id,
            "parent_id": self.parent_id, "name": self.name,
            "ts": self.t0_wall, "dur_s": dur, "status": status,
            "error": error, "attrs": _clean_attrs(attrs),
            "pid": os.getpid(), "tid": threading.get_ident()})
        if self._owns_finalize:
            t._finalize(self.ctx.trace_id, self.ctx.sampled,
                        root_dur_s=dur, force=force_keep)
        elif force_keep:
            # not the retention owner (e.g. the interpreter under a Bolt
            # session root): sticky-mark the trace so the owner keeps it
            t.force_keep(self.ctx.trace_id)


class Tracer:
    """Process-wide tracer: current-context registry + span buffers."""

    #: open (unfinalized) traces the buffer tolerates before evicting
    #: the oldest — orphans (a deadline-exceeded dispatch whose spans
    #: were never taken) must not leak unboundedly
    MAX_ACTIVE = 512

    def __init__(self) -> None:
        self._armed = _env_flag(ENV_ARM)
        self.sample_rate = _env_float(ENV_SAMPLE, 1.0)
        self.slow_ms = _env_float(ENV_SLOW_MS, 250.0)
        self.ring_cap = int(_env_float(ENV_RING, 256))
        self.xla_bridge = _env_flag(ENV_XLA)
        self._tls = threading.local()
        self._lock = threading.Lock()
        #: trace_id -> {"spans": [dict], "error": bool}
        self._active: dict[str, dict] = {}
        #: finalized, retained traces (each a list of span dicts)
        self._finished: list[list[dict]] = []
        self._counts = {"started": 0, "kept": 0, "dropped": 0}

    # --- arming ------------------------------------------------------------

    def enable(self, sample: float | None = None,
               slow_ms: float | None = None) -> None:
        if sample is not None:
            self.sample_rate = sample
        if slow_ms is not None:
            self.slow_ms = slow_ms
        self._armed = True

    def disable(self) -> None:
        self._armed = False

    def reset(self) -> None:
        with self._lock:
            self._active.clear()
            self._finished.clear()
            self._counts = {"started": 0, "kept": 0, "dropped": 0}

    # --- current context ----------------------------------------------------

    def _swap_current(self, ctx):
        prev = getattr(self._tls, "ctx", None)
        self._tls.ctx = ctx
        return prev

    def current(self) -> TraceContext | None:
        if not self._armed:
            return None
        return getattr(self._tls, "ctx", None)

    # --- span recording -----------------------------------------------------

    def _record(self, trace_id: str, span: dict) -> None:
        with self._lock:
            entry = self._active.get(trace_id)
            if entry is None:
                entry = {"spans": [], "error": False}
                self._active[trace_id] = entry
                self._counts["started"] += 1
                while len(self._active) > self.MAX_ACTIVE:
                    victim = next(iter(self._active))
                    del self._active[victim]
                    self._counts["dropped"] += 1
            entry["spans"].append(span)
            if span.get("status") == "error":
                entry["error"] = True

    def force_keep(self, trace_id: str) -> None:
        """Sticky keep-mark on a still-open trace (slow-query linkage)."""
        with self._lock:
            entry = self._active.get(trace_id)
            if entry is not None:
                entry["force"] = True

    def _finalize(self, trace_id: str, sampled: bool,
                  root_dur_s: float | None, force: bool = False) -> None:
        with self._lock:
            entry = self._active.pop(trace_id, None)
            if entry is None:
                return
            slow = root_dur_s is not None and \
                root_dur_s * 1000.0 >= self.slow_ms
            if not (force or entry.get("force") or sampled or slow
                    or entry["error"]):
                self._counts["dropped"] += 1
                return
            self._finished.append(entry["spans"])
            self._counts["kept"] += 1
            while len(self._finished) > self.ring_cap:
                self._finished.pop(0)

    def take_trace(self, trace_id: str) -> list[dict]:
        """Pop the spans accumulated for an ADOPTED trace, for shipping
        back to the process that owns the root."""
        with self._lock:
            entry = self._active.pop(trace_id, None)
        return entry["spans"] if entry else []

    def adopt_spans(self, spans) -> None:
        """Merge spans a remote process shipped back into their (still
        open) local trace."""
        if not self._armed or not spans:
            return
        for span in spans:
            tid = span.get("trace_id")
            if tid:
                self._record(tid, dict(span))

    # --- snapshots / exporters ---------------------------------------------

    def finished_traces(self) -> list[list[dict]]:
        with self._lock:
            return [list(spans) for spans in self._finished]

    def counts(self) -> dict:
        with self._lock:
            return dict(self._counts)

    # --- xla bridge ---------------------------------------------------------

    def _enter_xla(self, name: str):
        try:
            from jax.profiler import TraceAnnotation
            ann = TraceAnnotation(f"mgtrace:{name}")
            ann.__enter__()
            return ann
        except Exception as e:  # noqa: BLE001 — profiling never breaks serving
            log.debug("xla trace-annotation bridge unavailable: %s", e)
            return None

    def _exit_xla(self, ann) -> None:
        if ann is None:
            return
        try:
            ann.__exit__(None, None, None)
        except Exception as e:  # noqa: BLE001 — profiling never breaks serving
            log.debug("xla trace-annotation exit failed: %s", e)


TRACER = Tracer()


# --------------------------------------------------------------------------
# module-level API (what product code calls)
# --------------------------------------------------------------------------


def armed() -> bool:
    return TRACER._armed


def enable(sample: float | None = None, slow_ms: float | None = None) -> None:
    TRACER.enable(sample=sample, slow_ms=slow_ms)


def disable() -> None:
    TRACER.disable()


def span(name: str, **attrs):
    """Open a child span of the current context (context manager).

    Disarmed: returns the shared no-op (one attribute read + one call).
    The span object is truthy only when armed, so hot paths can guard
    attr computation with ``if sp:``.
    """
    t = TRACER
    if not t._armed:
        return _NOOP
    return _LiveSpan(t, name, t.current(), attrs)


def record_span(name: str, start_wall: float, duration_s: float,
                span_id: str | None = None, status: str = "ok",
                **attrs) -> None:
    """Atomically record a completed span under the current context —
    for extents whose start and end straddle protocol messages (e.g.
    query.execute across PULL batches). No begin/end imbalance is
    possible: one call, one span."""
    t = TRACER
    if not t._armed:
        return
    ctx = t.current()
    if ctx is None:
        return
    t._record(ctx.trace_id, {
        "trace_id": ctx.trace_id, "span_id": span_id or _new_id(),
        "parent_id": ctx.span_id, "name": name, "ts": start_wall,
        "dur_s": duration_s, "status": status, "error": None,
        "attrs": _clean_attrs(attrs), "pid": os.getpid(),
        "tid": threading.get_ident()})


def begin_trace(name: str, carrier: dict | None = None):
    """Mint the root of a locally-owned trace. Returns a TraceHandle (or
    None when disarmed); the owner must call ``handle.finish()`` exactly
    once. If a remote ``carrier`` (or an ambient local context) exists,
    the new root joins that trace as a child."""
    t = TRACER
    if not t._armed:
        return None
    parent = None
    edge = False
    if carrier and carrier.get("trace_id"):
        # a process-edge adoption (e.g. a Bolt client's carrier): this
        # handle is the local retention owner
        parent = TraceContext(str(carrier["trace_id"]),
                              str(carrier.get("span_id") or ""),
                              bool(carrier.get("sampled", True)))
        edge = True
    if parent is None:
        parent = t.current()
    if parent is not None:
        trace_id, sampled = parent.trace_id, parent.sampled
        parent_id = parent.span_id or None
    else:
        trace_id = _new_id(16)
        sampled = _sample_decision(trace_id, t.sample_rate)
        parent_id = None
    ctx = TraceContext(trace_id, _new_id(), sampled)
    return TraceHandle(t, name, ctx, parent_id,
                       owns_finalize=edge or parent_id is None)


def activate(ctx):
    """Make ``ctx`` (a TraceContext, e.g. ``handle.ctx``) current for
    the extent — the cross-thread continuation primitive. None → no-op."""
    if ctx is None or not TRACER._armed:
        return _NULL_ACTIVATION
    return _Activation(TRACER, ctx)


def adopt(carrier: dict | None, retain: bool = False):
    """Activate a REMOTE parent context from a wire carrier. Spans
    opened inside join the remote trace. retain=True finalizes the
    trace locally on exit (one-way hops); retain=False leaves the spans
    for take_trace() to ship back."""
    t = TRACER
    if not t._armed or not carrier or not carrier.get("trace_id"):
        return _NULL_ACTIVATION
    ctx = TraceContext(str(carrier["trace_id"]),
                       str(carrier.get("span_id") or ""),
                       bool(carrier.get("sampled", True)))
    return _Adoption(t, ctx, retain)


def inject() -> dict | None:
    """The wire carrier for the current context, or None."""
    ctx = TRACER.current()
    return ctx.carrier() if ctx is not None else None


def current_trace_id() -> str | None:
    ctx = TRACER.current()
    return ctx.trace_id if ctx is not None else None


def take_trace(trace_id: str) -> list[dict]:
    return TRACER.take_trace(trace_id)


def adopt_spans(spans) -> None:
    TRACER.adopt_spans(spans)


# --------------------------------------------------------------------------
# exporters
# --------------------------------------------------------------------------


def traces_json(trace_id: str | None = None) -> list[list[dict]]:
    """Retained traces (newest last), optionally filtered by id."""
    traces = TRACER.finished_traces()
    if trace_id:
        traces = [t for t in traces
                  if t and t[0].get("trace_id") == trace_id]
    return traces


def to_jsonl(traces=None) -> str:
    """One span per line — grep/jq-friendly archival form."""
    traces = TRACER.finished_traces() if traces is None else traces
    lines = []
    for spans in traces:
        for s in spans:
            lines.append(json.dumps(s, sort_keys=True, default=str))
    return "\n".join(lines) + ("\n" if lines else "")


def chrome_trace(traces=None) -> dict:
    """Chrome trace-event JSON (load in Perfetto / chrome://tracing).

    Complete ("X") events in microseconds; pid/tid preserved so a
    cross-process trace renders as lanes per process."""
    traces = TRACER.finished_traces() if traces is None else traces
    events = []
    for spans in traces:
        for s in spans:
            args = {"trace_id": s.get("trace_id"),
                    "span_id": s.get("span_id"),
                    "parent_id": s.get("parent_id"),
                    "status": s.get("status")}
            args.update(s.get("attrs") or {})
            if s.get("error"):
                args["error"] = s["error"]
            events.append({
                "name": s.get("name", "?"), "cat": "mgtrace", "ph": "X",
                "ts": float(s.get("ts", 0.0)) * 1e6,
                "dur": max(float(s.get("dur_s", 0.0)) * 1e6, 0.001),
                "pid": s.get("pid", 0), "tid": s.get("tid", 0),
                "args": args})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def write_jsonl(path: str) -> int:
    """Dump every retained span to a JSONL file; returns span count."""
    text = to_jsonl()
    with open(path, "w", encoding="utf-8") as f:
        f.write(text)
    return sum(1 for line in text.splitlines() if line)
