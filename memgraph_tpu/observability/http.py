"""Monitoring HTTP endpoint: Prometheus metrics + JSON status + traces
+ workload stats + readiness.

Counterpart of the reference's metrics/monitoring servers
(/root/reference/src/glue/PrometheusServerT.cpp, src/http_handlers/):
GET /metrics → Prometheus text; GET /status → JSON storage info;
GET /traces → retained mgtrace traces (JSON), ?format=chrome for
Chrome-trace-event JSON loadable in Perfetto, ?trace_id=<id> to fetch
the one trace a slow-query log line names; GET /stats → per-fingerprint
workload statistics (mgstat top-K, linked trace_ids, plan-cache hit
counts); GET /health → the saturation plane's readiness verdict —
HTTP 200 when ready, 503 with machine-readable reasons when any bounded
resource is saturated (the shape load balancers and admission control
consume).
"""

from __future__ import annotations

import asyncio
import json

from . import stats as mgstats
from . import trace as mgtrace
from .metrics import global_metrics


def _lane_stats() -> dict:
    """Compiled-read-lane residency table (import deferred: the lane
    lives in ops/, which must not load just to serve /metrics)."""
    try:
        from ..ops.pipeline import lane_stats
        return lane_stats()
    except Exception as e:  # noqa: BLE001 — stats must never break /stats
        import logging
        logging.getLogger(__name__).debug("lane stats unavailable: %s", e)
        return {"resident_programs": 0, "fingerprints": {}}


async def start_monitoring_server(host: str, port: int, ictx):
    async def handle(reader, writer):
        try:
            request = await asyncio.wait_for(reader.readline(), timeout=5)
            while True:
                line = await asyncio.wait_for(reader.readline(), timeout=5)
                if line in (b"\r\n", b"\n", b""):
                    break
            path = request.split()[1].decode() if request.split() else "/"
            status = "200 OK"
            if path.startswith("/metrics"):
                # --metrics-format picks the default payload; the
                # /metrics?format= query overrides per request
                fmt = ictx.config.get("metrics_format", "PROMETHEUS")
                if "format=json" in path.lower():
                    fmt = "JSON"
                elif "format=prometheus" in path.lower():
                    fmt = "PROMETHEUS"
                if fmt == "JSON":
                    body = json.dumps({
                        name: value for name, _k, value
                        in global_metrics.snapshot()})
                    ctype = "application/json"
                else:
                    body = global_metrics.prometheus_text()
                    ctype = "text/plain; version=0.0.4"
            elif path.startswith("/traces"):
                trace_id = None
                if "trace_id=" in path:
                    trace_id = path.split("trace_id=", 1)[1] \
                        .split("&", 1)[0]
                if "format=chrome" in path.lower():
                    body = json.dumps(mgtrace.chrome_trace(
                        mgtrace.traces_json(trace_id)))
                else:
                    body = json.dumps({
                        "armed": mgtrace.armed(),
                        "counts": mgtrace.TRACER.counts(),
                        "traces": mgtrace.traces_json(trace_id)},
                        default=str)
                ctype = "application/json"
            elif path.startswith("/stats"):
                # exception-flow contract surface (mgflow): refresh the
                # registry gauges on read — static by construction,
                # they move only when flowspec.py itself changes
                from ..flowspec import flow_stats
                flow = flow_stats()
                global_metrics.set_gauge("mgflow.contract_roots",
                                         float(flow["contract_roots"]))
                global_metrics.set_gauge("mgflow.escapes_total",
                                         float(flow["escapes_total"]))
                # mgstat workload statistics: bounded top-K fingerprints
                # with latency quantiles, error/plan-cache-hit counts,
                # and the retained trace_ids each shape links to
                body = json.dumps({
                    "enabled": mgstats.global_query_stats.enabled(),
                    "capacity": mgstats.global_query_stats.capacity,
                    "fingerprints": mgstats.global_query_stats.snapshot(),
                    # PPR serving plane: coalescing/cache counters
                    # (local, plus the daemon's mirrored gauges)
                    "ppr": {name: value for name, _k, value
                            in global_metrics.snapshot()
                            if name.startswith(
                                ("ppr.", "kernel_server.daemon.ppr."))},
                    # device compile plane: the runtime witness for the
                    # mgxla static compile budget (jit.compile_total)
                    "device": {name: value for name, _k, value
                               in global_metrics.snapshot()
                               if name.startswith("jit.")},
                    # incremental analytics plane (r19, mgdelta):
                    # delta applies/compactions/fallbacks, warm-start
                    # counters, resident-generation gauge (local plus
                    # the daemon's counters mirrored through health)
                    "delta": {name: value for name, _k, value
                              in global_metrics.snapshot()
                              if name.startswith(
                                  ("delta.",
                                   "kernel_server.daemon.delta."))},
                    # sharded OLTP execution plane (r18, mgshard):
                    # per-shard ops/latency/queue-depth, 2PC counters,
                    # move durations, routing-table epoch
                    "sharding": {name: value for name, _k, value
                                 in global_metrics.snapshot()
                                 if name.startswith("shard.")},

                    # out-of-core streamed tier (r21, mgtier):
                    # admission verdicts, blocks/bytes streamed,
                    # compression + overlap histograms (local plus the
                    # daemon's counters mirrored through health)
                    "tier": {name: value for name, _k, value
                             in global_metrics.snapshot()
                             if name.startswith(
                                 ("tier.",
                                  "kernel_server.daemon.tier."))},
                    # streaming ingestion plane (r17, mgstream):
                    # batch/record counters, redeliveries, dead-letter
                    # quarantine, backpressure pauses, per-stream lag
                    # gauges — plus the trigger firing/error counters
                    # that ride the same ingest path
                    "streams": {name: value for name, _k, value
                                in global_metrics.snapshot()
                                if name.startswith(
                                    ("stream.", "trigger."))},
                    # device memory accounting plane (mgmem): the
                    # admission budget vs the modeled resident peak —
                    # the headroom capacity planning reads (local
                    # gauges plus the daemon's mirror through health)
                    "memory": {name: value for name, _k, value
                               in global_metrics.snapshot()
                               if name.startswith(
                                   ("kernel_server.hbm_",
                                    "kernel_server.daemon.hbm_"))},
                    # compiled Cypher read lane (r20, mglane):
                    # compile/hit/typed-fallback counters plus the
                    # per-fingerprint lane residency table
                    "lane": dict(_lane_stats(), metrics={
                        name: value for name, _k, value
                        in global_metrics.snapshot()
                        if name.startswith("lane.")}),
                    # exception-flow contracts (mgflow, r24): the
                    # declared serving-root contracts and wire ids —
                    # the surface `python -m tools.mgflow check` gates
                    "flow": flow},
                    default=str)
                ctype = "application/json"
            elif path.startswith("/health"):
                verdict = mgstats.global_saturation.evaluate(ictx)
                if not verdict["ready"]:
                    status = "503 Service Unavailable"
                body = json.dumps(verdict, default=str)
                ctype = "application/json"
            else:
                info = dict(ictx.storage.info())
                with ictx._rq_lock:
                    info["running_queries"] = len(ictx.running_queries)
                body = json.dumps(info)
                ctype = "application/json"
            payload = body.encode("utf-8")
            writer.write(
                f"HTTP/1.1 {status}\r\n".encode()
                + f"Content-Type: {ctype}\r\n".encode()
                + f"Content-Length: {len(payload)}\r\n".encode()
                + b"Connection: close\r\n\r\n" + payload)
            await writer.drain()
        except (OSError, ValueError):
            # OSError: client went away mid-response. ValueError: a
            # stats payload json.dumps refused (circular/oversized
            # object) — drop this response, never the serving task
            pass
        finally:
            writer.close()

    return await asyncio.start_server(handle, host, port)
