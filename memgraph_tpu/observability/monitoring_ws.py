"""Websocket monitoring server: live log streaming + metrics pull.

Counterpart of the reference's monitoring websocket
(/root/reference/src/communication/websocket/{listener,session}.cpp,
wired at memgraph.cpp:1033-1044): Lab connects to --monitoring-port,
optionally authenticates with a {"username", "password"} JSON frame,
and receives every log line as it is emitted (the reference broadcasts
its spdlog sink via Listener::WriteToAll; here a logging.Handler
broadcasts to all authenticated sessions). A {"command": "show_metrics"}
frame answers with a metrics snapshot.

The RFC 6455 implementation is hand-rolled on stdlib sockets — no
external websocket dependency exists in this image, and the subset
needed (HTTP upgrade, masked client frames, unmasked server frames,
ping/pong/close) is small.
"""

from __future__ import annotations

import base64
import hashlib
import json
import logging
import queue
import socket
import struct
import threading
import time

log = logging.getLogger(__name__)

_GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"


# --------------------------------------------------------------------------
# frame codec
# --------------------------------------------------------------------------

def encode_frame(payload: bytes, opcode: int = 0x1) -> bytes:
    """Server->client frame (FIN set, unmasked)."""
    head = bytes([0x80 | opcode])
    n = len(payload)
    if n < 126:
        head += bytes([n])
    elif n < (1 << 16):
        head += bytes([126]) + struct.pack(">H", n)
    else:
        head += bytes([127]) + struct.pack(">Q", n)
    return head + payload


def _read_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return bytes(buf)


def decode_frame(sock: socket.socket):
    """Read one client frame -> (opcode, payload). Client frames MUST be
    masked per RFC 6455 §5.1; unmasked ones close the connection."""
    b0, b1 = _read_exact(sock, 2)
    opcode = b0 & 0x0F
    masked = bool(b1 & 0x80)
    n = b1 & 0x7F
    if n == 126:
        (n,) = struct.unpack(">H", _read_exact(sock, 2))
    elif n == 127:
        (n,) = struct.unpack(">Q", _read_exact(sock, 8))
    if not masked:
        raise ConnectionError("unmasked client frame")
    mask = _read_exact(sock, 4)
    data = bytearray(_read_exact(sock, n))
    for i in range(n):
        data[i] ^= mask[i & 3]
    return opcode, bytes(data)


def _handshake(sock: socket.socket) -> bool:
    """Read the HTTP upgrade request, answer 101. False on anything else."""
    data = b""
    while b"\r\n\r\n" not in data:
        chunk = sock.recv(4096)
        if not chunk or len(data) > 65536:
            return False
        data += chunk
    headers = {}
    for line in data.split(b"\r\n")[1:]:
        if b":" in line:
            k, v = line.split(b":", 1)
            headers[k.strip().lower()] = v.strip()
    key = headers.get(b"sec-websocket-key")
    if key is None or b"websocket" not in headers.get(b"upgrade", b"").lower():
        sock.sendall(b"HTTP/1.1 400 Bad Request\r\n\r\n")
        return False
    accept = base64.b64encode(hashlib.sha1(
        key + _GUID.encode()).digest()).decode()
    sock.sendall(
        ("HTTP/1.1 101 Switching Protocols\r\n"
         "Upgrade: websocket\r\n"
         "Connection: Upgrade\r\n"
         f"Sec-WebSocket-Accept: {accept}\r\n\r\n").encode())
    return True


# --------------------------------------------------------------------------
# server
# --------------------------------------------------------------------------

class MonitoringServer:
    """Threaded websocket endpoint broadcasting logs + serving metrics.

    auth: optional memgraph_tpu.auth.Auth — when it has users, sessions
    must authenticate before receiving anything (reference: session.cpp
    refuses unauthenticated traffic when access control is on).
    """

    # log records queued for broadcast before the drain thread drops them
    QUEUE_CAPACITY = 1024

    def __init__(self, host: str = "0.0.0.0", port: int = 7444,
                 auth=None, metrics=None) -> None:
        self.host, self.port = host, port
        self.auth = auth
        self.metrics = metrics
        from ..utils.locks import tracked_lock
        from ..utils.sanitize import shared_field
        self._sessions: list = []       # (socket, lock) of live sessions
        self._lock = tracked_lock("MonitoringServer._lock")
        self._srv: socket.socket | None = None
        self._stop = threading.Event()
        self._log_handler: logging.Handler | None = None
        # broadcast() is called from INSIDE a logging.Handler on arbitrary
        # threads; network sends happen only on the drain thread below, so
        # a stalled monitoring client can never block a writer thread
        import queue as _queue
        self._queue: _queue.Queue = _queue.Queue(self.QUEUE_CAPACITY)
        # drop counting is a read-modify-write from arbitrary logging
        # threads: it needs its own leaf lock, not the sessions lock
        self._stats_lock = tracked_lock("MonitoringServer._stats_lock")
        self.dropped_records = 0
        shared_field(self, "_sessions", "dropped_records")

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((self.host, self.port))
        self.port = self._srv.getsockname()[1]
        self._srv.listen(16)
        self._srv.settimeout(0.5)
        threading.Thread(target=self._accept_loop, daemon=True,
                         name="monitoring-ws").start()
        threading.Thread(target=self._drain_loop, daemon=True,
                         name="monitoring-ws-broadcast").start()
        self._log_handler = _BroadcastHandler(self)
        self._log_handler.setLevel(logging.INFO)
        logging.getLogger().addHandler(self._log_handler)

    def stop(self) -> None:
        self._stop.set()
        if self._log_handler is not None:
            logging.getLogger().removeHandler(self._log_handler)
        try:
            self._queue.put_nowait(None)    # wake the drain thread
        except queue.Full:   # drain sees _stop on its next timeout
            pass
        with self._lock:
            sessions = list(self._sessions)
            self._sessions.clear()
        for sock, _lk in sessions:
            try:
                sock.close()
            except OSError:
                pass
        if self._srv is not None:
            self._srv.close()

    # -- broadcast ----------------------------------------------------------

    def broadcast(self, obj: dict) -> None:
        """Enqueue for the drain thread; NEVER touches the network on the
        caller's thread. A full queue drops the record (counted) rather
        than exerting backpressure on whoever is logging."""
        try:
            self._queue.put_nowait(obj)
        except queue.Full:
            # racy `self.dropped_records += 1` lost drops under
            # concurrent logging (mgsan write-write race, PR 4 sweep)
            from ..utils.sanitize import shared_write
            with self._stats_lock:
                shared_write(self, "dropped_records")
                self.dropped_records += 1

    def _drain_loop(self) -> None:
        import queue as _queue
        while not self._stop.is_set():
            try:
                obj = self._queue.get(timeout=0.5)
            except _queue.Empty:
                continue
            if obj is None:
                continue
            self._send_to_sessions(obj)

    def _send_to_sessions(self, obj: dict) -> None:
        frame = encode_frame(json.dumps(obj).encode("utf-8"))
        with self._lock:
            sessions = list(self._sessions)
        dead = []
        for sock, lk in sessions:
            try:
                with lk:
                    sock.sendall(frame)
            except (OSError, socket.timeout):
                # includes send timeouts: slow/stalled clients are
                # dropped rather than ever stalling the drain thread
                dead.append((sock, lk))
        if dead:
            with self._lock:
                for s in dead:
                    if s in self._sessions:
                        self._sessions.remove(s)

    # -- internals ----------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=self._session, args=(conn,),
                             daemon=True).start()

    def _needs_auth(self) -> bool:
        return self.auth is not None and bool(self.auth.users())

    def _session(self, conn: socket.socket) -> None:
        try:
            conn.settimeout(30.0)
            if not _handshake(conn):
                conn.close()
                return
            lk = threading.Lock()
            authenticated = not self._needs_auth()
            if not authenticated:
                opcode, payload = decode_frame(conn)
                ok = False
                try:
                    creds = json.loads(payload)
                    ok = self.auth.authenticate(
                        str(creds.get("username", "")),
                        str(creds.get("password", "")))
                except (ValueError, KeyError):
                    ok = False
                with lk:
                    conn.sendall(encode_frame(json.dumps({
                        "success": bool(ok),
                        "message": ("User has been successfully "
                                    "authenticated!") if ok
                        else "Authentication failed!"}).encode()))
                if not ok:
                    conn.close()
                    return
                authenticated = True
            # finite timeout on BOTH directions: a stalled client must
            # never block broadcast() (its send fails after 5s and the
            # session is dropped) — the recv loop treats the timeout as
            # "no request yet" and keeps serving
            conn.settimeout(5.0)
            with self._lock:
                self._sessions.append((conn, lk))
            # request loop: metrics pull, ping/pong, close
            while not self._stop.is_set():
                try:
                    opcode, payload = decode_frame(conn)
                except socket.timeout:
                    continue
                if opcode == 0x8:            # close
                    break
                if opcode == 0x9:            # ping -> pong
                    with lk:
                        conn.sendall(encode_frame(payload, opcode=0xA))
                    continue
                if opcode != 0x1:
                    continue
                try:
                    req = json.loads(payload)
                except ValueError:
                    continue
                if req.get("command") == "show_metrics":
                    snap = (self.metrics.snapshot()
                            if self.metrics is not None else {})
                    with lk:
                        conn.sendall(encode_frame(json.dumps(
                            {"event": "metrics", "metrics": snap,
                             "timestamp": time.time()}).encode()))
        except (ConnectionError, OSError, struct.error):
            pass
        finally:
            with self._lock:
                self._sessions = [s for s in self._sessions
                                  if s[0] is not conn]
            try:
                conn.close()
            except OSError:
                pass


class _BroadcastHandler(logging.Handler):
    """Root-logger handler pushing every record to all sessions (the
    reference registers a spdlog sink that does Listener::WriteToAll)."""

    def __init__(self, server: MonitoringServer) -> None:
        super().__init__()
        self._server = server
        self._emitting = threading.local()

    def emit(self, record: logging.LogRecord) -> None:
        if getattr(self._emitting, "on", False):
            return      # a broadcast-triggered log must not recurse
        self._emitting.on = True
        try:
            self._server.broadcast({
                "event": "log",
                "level": record.levelname.lower(),
                "message": record.getMessage(),
                "logger": record.name,
                "timestamp": record.created,
            })
        # mglint: disable=MG003 — a logging handler must never throw into
        # the emitting thread; broadcast() already counts drops
        except Exception:   # noqa: BLE001 — logging must never throw
            pass
        finally:
            self._emitting.on = False
