"""Replica-side replication server.

Counterpart of the reference's replica handlers
(/root/reference/src/dbms/replication_handlers.cpp): accepts a MAIN's
registration, ingests a full snapshot transfer for catch-up, then applies
WAL transaction frames in commit order. Applies bypass MVCC (the replica's
state is always a prefix of MAIN's committed history) — the same direct-
apply model the reference uses on replicas.
"""

from __future__ import annotations

import logging
import socket
import struct
import threading

from ..exceptions import MemgraphTpuError
from ..observability import trace as mgtrace
from ..storage.durability import wal as W
from ..utils.locks import tracked_lock
from ..storage.durability.recovery import _apply_wal_txn
from . import protocol as P

log = logging.getLogger(__name__)


class ReplicaServer:
    """Listens for the MAIN; applies snapshot + WAL frames to storage."""

    def __init__(self, storage, host: str = "127.0.0.1", port: int = 10000,
                 ictx=None, fencing_epoch: int = 0):
        self.storage = storage
        self.ictx = ictx           # for system-state apply (auth, multi-db)
        self.host = host
        self.port = port
        self.last_system_seq = 0
        self.last_commit_ts = 0
        # fencing: the highest promotion epoch this replica has ever
        # heard (from its own demote RPC or a registering MAIN). A MAIN
        # registering with a LOWER epoch was deposed — its registration
        # is refused with MSG_FENCED so a partitioned-away old MAIN can
        # never feed us stale writes (split-brain guard).
        self.fencing_epoch = int(fencing_epoch or 0)
        self.epoch = self.fencing_epoch    # back-compat alias
        self._sock: socket.socket | None = None
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self._apply_lock = tracked_lock("ReplicaServer._apply_lock")
        self._conns: list[socket.socket] = []
        # 2PC (STRICT_SYNC): frames received via MSG_PREPARE wait here for
        # the MAIN's MSG_FINALIZE decision (reference: PrepareCommit /
        # FinalizeCommit RPCs, storage/v2/replication/rpc.hpp:59-98)
        self._pending_2pc: dict[int, bytes] = {}

    def start(self) -> None:
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((self.host, self.port))
        self.port = self._sock.getsockname()[1]  # resolve port 0 for tests
        self._sock.listen(4)
        self._thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._sock is not None:
            try:
                # shutdown() wakes the blocked accept() — close() alone
                # leaves the accept thread holding the fd, so the port
                # stays bound and a REPLICA->MAIN->REPLICA role flip on
                # the same port fails with EADDRINUSE
                self._sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._sock.close()
            except OSError:
                pass
        for conn in list(self._conns):
            try:
                conn.close()
            except OSError:
                pass
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, addr = self._sock.accept()
            except OSError:
                return
            self._conns.append(conn)
            t = threading.Thread(target=self._serve_main, args=(conn,),
                                 daemon=True)
            t.start()

    def _serve_main(self, conn: socket.socket) -> None:
        try:
            # TLS handshake on THIS thread (accept loop must never block
            # on a silent peer); timeout inside wrap_cluster_server
            from ..utils.tls import wrap_cluster_server
            conn = wrap_cluster_server(conn)
            while not self._stop.is_set():
                msg_type, payload = P.recv_frame(conn)
                # armed repl.recv faults sever the connection before the
                # received frame is applied or acked (a lost-frame /
                # crashed-replica stand-in) — the MAIN must heal via its
                # retry/catch-up path
                from ..utils import faultinject as FI
                if FI.fire("repl.recv") == "drop":
                    raise FI.FaultInjected("injected drop of received frame")
                if msg_type == P.MSG_REGISTER:
                    info = P.parse_json(payload)
                    main_epoch = int(info.get("epoch") or 0)
                    if main_epoch < self.fencing_epoch:
                        # deposed MAIN: refuse — and TELL it the current
                        # epoch so it can fence itself immediately
                        log.warning(
                            "refusing registration from stale-epoch main "
                            "(theirs %d < ours %d)", main_epoch,
                            self.fencing_epoch)
                        P.send_json(conn, P.MSG_FENCED,
                                    {"fencing_epoch": self.fencing_epoch})
                        continue
                    self.fencing_epoch = max(self.fencing_epoch,
                                             main_epoch)
                    self.epoch = self.fencing_epoch
                    # a (re-)registering MAIN supersedes any in-flight 2PC:
                    # prepared-but-unfinalized frames from the previous
                    # connection would otherwise leak forever
                    self._pending_2pc.clear()
                    P.send_json(conn, P.MSG_REGISTER_OK,
                                {"last_commit_ts": self.last_commit_ts,
                                 "epoch": self.fencing_epoch})
                elif msg_type == P.MSG_SNAPSHOT:
                    self._pending_2pc.clear()
                    self._apply_snapshot_bytes(payload)
                    P.send_json(conn, P.MSG_ACK,
                                {"last_commit_ts": self.last_commit_ts})
                elif msg_type == P.MSG_WAL_FRAME:
                    self._apply_wal_frame(payload)
                    P.send_json(conn, P.MSG_ACK,
                                {"last_commit_ts": self.last_commit_ts})
                elif msg_type == P.MSG_PREPARE:
                    # phase 1: durably hold the frame, vote yes
                    txns = list(W.iter_txns_from_bytes(payload))
                    for commit_ts, _ in txns:
                        self._pending_2pc[commit_ts] = payload
                    P.send_json(conn, P.MSG_ACK,
                                {"last_commit_ts": self.last_commit_ts,
                                 "prepared": [ts for ts, _ in txns]})
                elif msg_type == P.MSG_FINALIZE:
                    info = P.parse_json(payload)
                    commit_ts = info["commit_ts"]
                    frame = self._pending_2pc.pop(commit_ts, None)
                    if info.get("decision") == "commit" and frame is not None:
                        self._apply_wal_frame(frame)
                    P.send_json(conn, P.MSG_ACK,
                                {"last_commit_ts": self.last_commit_ts})
                elif msg_type == P.MSG_SYSTEM:
                    self._apply_system(P.parse_json(payload))
                    P.send_json(conn, P.MSG_ACK,
                                {"last_commit_ts": self.last_commit_ts,
                                 "system_seq": self.last_system_seq})
                elif msg_type == P.MSG_HEARTBEAT:
                    P.send_json(conn, P.MSG_ACK,
                                {"last_commit_ts": self.last_commit_ts})
                else:
                    P.send_json(conn, P.MSG_ERROR,
                                {"message": f"unknown message {msg_type}"})
        except (ConnectionError, OSError):
            pass
        except (struct.error, ValueError, MemgraphTpuError) as e:
            # corrupt frame (torn length prefix, garbage JSON) or a
            # refused apply (DurabilityError/StorageError): sever THIS
            # connection loudly instead of killing the serving thread
            # silently — the MAIN heals via its retry/catch-up path
            log.warning("replica connection dropped: %s: %s",
                        type(e).__name__, e)
        finally:
            conn.close()

    def apply_pending_2pc(self) -> int:
        """Presumed-commit on promotion: apply prepared-but-unfinalized
        2PC frames in commit order before this replica becomes MAIN.

        A frame sits here only after the old MAIN collected the full
        strict vote — the common reason the finalize never arrived is
        that the MAIN committed (and ACKED the client) and then lost us.
        Applying is therefore the durability-safe direction; the rare
        aborted-after-vote txn resurfaces as an UN-acked write, which no
        client was promised anything about. Returns the applied count."""
        pending = sorted(self._pending_2pc.items())
        self._pending_2pc.clear()
        for commit_ts, frame in pending:
            if commit_ts <= self.last_commit_ts:
                continue
            self._apply_wal_frame(frame)
        if pending:
            log.warning("promotion: presumed-commit applied %d pending "
                        "2PC frame(s)", len(pending))
        return len(pending)

    # --- appliers -----------------------------------------------------------

    def _apply_snapshot_bytes(self, data: bytes) -> None:
        import os
        import tempfile
        from ..storage.durability.recovery import (_apply_snapshot,
                                                   _clear_storage)
        from ..storage.durability.snapshot import load_snapshot
        with self._apply_lock:
            with tempfile.NamedTemporaryFile(delete=False,
                                             suffix=".mgsnap") as f:
                f.write(data)
                path = f.name
            try:
                parsed = load_snapshot(path)
                _clear_storage(self.storage)
                _apply_snapshot(self.storage, parsed)
                self.last_commit_ts = parsed["timestamp"]
                self.storage._bump_topology()
            finally:
                os.unlink(path)

    def _apply_system(self, txn: dict) -> None:
        """Apply an ordered system transaction (auth / multi-db DDL) —
        the replica-side half of the reference's system::Transaction
        (/root/reference/src/system/transaction.cpp). Deliveries are
        full-state (auth) or idempotent DDL, so replays are harmless."""
        carrier = txn.pop("trace", None)
        with mgtrace.adopt(carrier, retain=True):
            with mgtrace.span("repl.apply") as sp:
                if sp:
                    sp.set(kind=str(txn.get("kind")),
                           seq=txn.get("seq", 0))
                self._apply_system_inner(txn)

    def _apply_system_inner(self, txn: dict) -> None:
        seq = txn.get("seq", 0)
        kind = txn.get("kind")
        if kind == "full":
            # a full-state dump re-baselines the sequence: a restarted MAIN
            # starts its seq counter over
            self.last_system_seq = 0
        elif seq and seq <= self.last_system_seq:
            return
        data = txn.get("data") or {}
        ictx = self.ictx
        if kind in ("auth", "full") and ictx is not None:
            auth = getattr(ictx, "auth_store", None)
            if auth is None:
                from ..auth.auth import Auth
                auth = Auth()
                ictx.auth_store = auth
            dump = data.get("auth") if kind == "full" else data
            if dump is not None:
                auth.apply_dict(dump)
        if kind in ("db_create", "db_drop", "full") and ictx is not None:
            dbms = getattr(ictx, "dbms", None)
            if dbms is not None:
                if kind == "db_create":
                    names = [data["name"]]
                elif kind == "full":
                    names = data.get("databases", [])
                else:
                    names = []
                for name in names:
                    if name not in dbms.names():
                        dbms.create(name)
                if kind == "db_drop" and data["name"] in dbms.names():
                    dbms.drop(data["name"])
        if kind in ("db_suspend", "db_resume") and ictx is not None:
            dbms = getattr(ictx, "dbms", None)
            if dbms is not None:
                try:
                    if kind == "db_suspend":
                        dbms.suspend(data["name"])
                    else:
                        dbms.resume(data["name"])
                except Exception:  # noqa: BLE001 — idempotent replays
                    log.debug("system txn %s for %r already applied "
                              "(idempotent replay)", kind,
                              data.get("name"), exc_info=True)
        if seq:
            self.last_system_seq = seq

    def _apply_wal_frame(self, frame: bytes) -> None:
        with self._apply_lock:
            changed: set = set()
            for commit_ts, ops in W.iter_txns_from_bytes(frame):
                if commit_ts <= self.last_commit_ts:
                    continue  # duplicate delivery (idempotent)
                changed |= _apply_wal_txn(self.storage, ops)
                self.last_commit_ts = commit_ts
                self.storage._timestamp = max(self.storage._timestamp,
                                              commit_ts)
            # version-keyed delta caches (vector index) refresh O(delta)
            # on replicas too — WAL apply records its changed gids
            self.storage._bump_topology(changed)
