"""Replication wire protocol: framing + message types.

Counterpart of the reference's replication RPCs
(/root/reference/src/storage/v2/replication/rpc.hpp:59-239 —
PrepareCommit/FinalizeCommit/Heartbeat/Snapshot/CurrentWal) over the
reference's SLK-style length-prefixed binary framing (src/rpc, src/slk):
here the payloads reuse the WAL frame encoding (storage/durability/wal.py)
so the replica applies exactly what durability writes.

Frame: [u32 length][u8 type][payload]
"""

from __future__ import annotations

import json
import socket
import struct

MSG_REGISTER = 0x01       # json: {name, epoch, start_ts}
MSG_REGISTER_OK = 0x02    # json: {last_commit_ts, epoch}
MSG_SNAPSHOT = 0x03       # raw snapshot bytes (full state transfer)
MSG_WAL_FRAME = 0x04      # raw wal txn frame (commit application)
MSG_HEARTBEAT = 0x05      # json: {main_commit_ts}
MSG_ACK = 0x06            # json: {last_commit_ts}
MSG_PREPARE = 0x07        # 2PC phase 1: wal frame held pending a decision
MSG_FINALIZE = 0x08       # 2PC phase 2: json {commit_ts, decision}
MSG_SYSTEM = 0x09         # json: ordered system txn (auth / multi-db DDL)
MSG_FENCED = 0x0A         # json: {fencing_epoch} — registration refused,
                          # the sender's epoch is stale (a deposed MAIN)
MSG_ERROR = 0x7F          # json: {message}


def send_frame(sock: socket.socket, msg_type: int, payload: bytes) -> None:
    sock.sendall(struct.pack(">IB", len(payload) + 1, msg_type) + payload)


def recv_exact(sock: socket.socket, n: int) -> bytes:
    out = b""
    while len(out) < n:
        chunk = sock.recv(n - len(out))
        if not chunk:
            raise ConnectionError("replication connection closed")
        out += chunk
    return out


def recv_frame(sock: socket.socket) -> tuple[int, bytes]:
    header = recv_exact(sock, 5)
    length, msg_type = struct.unpack(">IB", header)
    payload = recv_exact(sock, length - 1) if length > 1 else b""
    return msg_type, payload


def send_json(sock, msg_type: int, obj) -> None:
    send_frame(sock, msg_type, json.dumps(obj).encode("utf-8"))


def parse_json(payload: bytes):
    return json.loads(payload.decode("utf-8"))
