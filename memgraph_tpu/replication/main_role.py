"""MAIN-side replication: per-replica clients, modes, catch-up.

Counterpart of the reference's replication client/handler
(/root/reference/src/replication_handler/replication_handler.cpp,
storage/v2/replication/): one connection per registered replica; commits
ship as WAL frames. Modes (replication_coordination_glue/mode.hpp:22):

  SYNC        — the committing thread waits for the replica's ack
  ASYNC       — frames queue onto a background worker
  STRICT_SYNC — like SYNC, and a failed ack marks the commit degraded
                (full 2PC vote-before-visibility is the HA follow-up)

Catch-up: on registration (or reconnect) the replica receives a full
snapshot transfer, then live frames — the reference's snapshot→WAL
catch-up ladder collapsed to its snapshot rung (recovery.hpp analog).
"""

from __future__ import annotations

import enum
import logging
import queue
import socket
import threading
import time
from collections import deque

from ..observability import trace as mgtrace
from ..observability.metrics import global_metrics
from ..utils import faultinject as FI
from ..utils.locks import tracked_lock
from ..utils.retry import RetryPolicy
from . import protocol as P

log = logging.getLogger(__name__)


class ReplicationMode(enum.Enum):
    SYNC = "sync"
    ASYNC = "async"
    STRICT_SYNC = "strict_sync"


class ReplicaStatus(enum.Enum):
    READY = "ready"
    REPLICATING = "replicating"
    RECOVERY = "recovery"
    INVALID = "invalid"


class FencedError(OSError):
    """A replica refused this MAIN's registration: its fencing epoch is
    newer — a successor MAIN was promoted. OSError subclass so generic
    network handlers treat it as a dead link, but carries the observed
    epoch so ReplicationState can fence itself on sight."""

    def __init__(self, observed_epoch: int):
        super().__init__(
            f"fenced: a main with epoch {observed_epoch} superseded us")
        self.observed_epoch = observed_epoch


class ReplicaClient:
    def __init__(self, name: str, address: str, mode: ReplicationMode,
                 storage, src_node: str = "main", epoch_fn=None):
        from ..exceptions import QueryException
        self.name = name
        self.address = address
        # logical node identities for the nemesis network model: every
        # message direction main→replica / replica→main consults the
        # (src, dst)-keyed link rules in utils/faultinject
        self.src_node = src_node
        # current fencing epoch, read at (re-)registration time — a
        # callable because reconnects may happen after a demote/promote
        # changed the owning state's epoch
        self.epoch_fn = epoch_fn or (lambda: 0)
        host, _, port = address.rpartition(":")
        if not host or not port.isdigit():
            raise QueryException(
                f"replica address must be 'host:port', got {address!r}")
        self._host, self._port = host, int(port)
        self.mode = mode
        self.storage = storage
        self.status = ReplicaStatus.INVALID
        # self-healing: ONE shared backoff policy for every RPC site and
        # the reconnect loop (replaces the old per-site except blocks);
        # exhausting it lets a STRICT_SYNC replica degrade instead of
        # wedging commits forever
        self.retry_policy = RetryPolicy(base_delay=0.1, max_delay=5.0,
                                        max_retries=5)
        # health bookkeeping is touched by the commit path, the heartbeat
        # thread, and per-replica reconnect workers concurrently; the
        # streak/backoff counters are read-modify-writes, so they share a
        # dedicated leaf lock (mgsan found lost increments here)
        from ..utils.locks import tracked_lock
        from ..utils.sanitize import shared_field
        self._health_lock = tracked_lock("ReplicaClient._health_lock")
        self.last_acked_ts = 0
        self.failures = 0              # consecutive failed RPCs
        self.degraded_from_strict = False
        self._reconnect_attempts = 0
        self._next_reconnect_at = 0.0
        shared_field(self, "last_acked_ts", "failures",
                     "_reconnect_attempts", "_next_reconnect_at")
        self._sock: socket.socket | None = None
        self._lock = tracked_lock("ReplicaClient._lock")
        self._queue: "queue.Queue[bytes]" = queue.Queue(maxsize=10_000)
        self._worker: threading.Thread | None = None
        self._stop = threading.Event()
        # frames committed while catch-up is in flight buffer here; the
        # replica dedups by commit_ts, so replay overlap is harmless
        self._catchup_buffer: list[bytes] = []
        self._catchup_system: list[dict] = []
        self._system_queue: list[dict] = []
        self._syslock = tracked_lock("ReplicaClient._syslock")
        self._sys_draining = False
        self.catchup_used: str | None = None   # "wal_delta" | "snapshot"
        # serializes catch-up attempts: the registering thread and the
        # heartbeat reconnect may target the same client concurrently
        self._catchup_lock = tracked_lock("ReplicaClient._catchup_lock")

    # --- connection / catch-up ----------------------------------------------

    def connect_and_catch_up(self) -> None:
        with self._catchup_lock:
            if self.status is ReplicaStatus.READY:
                return            # another thread just finished catch-up
            try:
                self._connect_and_catch_up()
            except BaseException:
                # a half-done catch-up must not linger in RECOVERY: ship()
                # would buffer frames into _catchup_buffer forever
                self.status = ReplicaStatus.INVALID
                raise

    def _net_out(self) -> None:
        """Nemesis link check, main→replica direction: a partitioned
        link means the message never leaves this node."""
        if FI.net_fire(self.src_node, self.name) == "drop":
            raise FI.FaultInjected(
                f"link {self.src_node}->{self.name} partitioned")

    def _net_in(self) -> None:
        """Nemesis link check, replica→main direction, applied AFTER the
        peer processed the message: with an asymmetric partition the
        replica DID apply the frame but the ack is lost on the wire."""
        if FI.net_fire(self.name, self.src_node) == "drop":
            raise FI.FaultInjected(
                f"link {self.name}->{self.src_node} partitioned (ack lost)")

    def _connect_and_catch_up(self) -> None:
        self.status = ReplicaStatus.RECOVERY
        self._net_out()
        sock = socket.create_connection((self._host, self._port), timeout=30)
        from ..utils.tls import wrap_cluster_client
        sock = wrap_cluster_client(sock, server_hostname=self._host)
        P.send_json(sock, P.MSG_REGISTER,
                    {"name": self.name, "epoch": self.epoch_fn(),
                     "src": self.src_node,
                     "main_commit_ts": self.storage.latest_commit_ts()})
        msg_type, payload = P.recv_frame(sock)
        if msg_type == P.MSG_FENCED:
            sock.close()
            raise FencedError(P.parse_json(payload).get("fencing_epoch", 0))
        if msg_type != P.MSG_REGISTER_OK:
            sock.close()
            raise ConnectionError("replica registration failed")
        try:
            self._net_in()
        except FI.FaultInjected:
            sock.close()
            raise
        info = P.parse_json(payload)
        self._sock = sock
        # catch-up ladder (reference recovery.hpp): WAL-delta rung first —
        # a briefly-behind replica receives only the missed commit frames;
        # snapshot rung when the ring no longer covers its position
        replica_ts = info.get("last_commit_ts", 0)
        if replica_ts < self.storage.latest_commit_ts():
            frames = None
            provider = getattr(self, "recent_frames_provider", None)
            if provider is not None:
                frames = provider(replica_ts)
            if frames is not None:
                self.catchup_used = "wal_delta"
                for frame in frames:
                    self._net_out()
                    P.send_frame(sock, P.MSG_WAL_FRAME, frame)
                    msg_type, payload = P.recv_frame(sock)
                    self._net_in()
                    if msg_type != P.MSG_ACK:
                        raise ConnectionError("wal-delta catch-up failed")
                    self._set_acked(
                        P.parse_json(payload)["last_commit_ts"])
            else:
                self.catchup_used = "snapshot"
                snapshot_bytes = self._snapshot_bytes()
                self._net_out()
                P.send_frame(sock, P.MSG_SNAPSHOT, snapshot_bytes)
                msg_type, payload = P.recv_frame(sock)
                self._net_in()
                if msg_type != P.MSG_ACK:
                    raise ConnectionError("snapshot transfer failed")
                self._set_acked(
                    P.parse_json(payload)["last_commit_ts"])
        # system-state catch-up: full auth + database list (idempotent)
        state_provider = getattr(self, "system_state_provider", None)
        if state_provider is not None:
            full = state_provider()
            if full:
                with self._lock:
                    self._send_system_locked({"seq": 0, "kind": "full",
                                              "data": full})
        # drain anything committed while catch-up ran, then go live; the
        # status flip and the drain share the lock so no frame slips between
        with self._lock:
            buffered = self._catchup_buffer
            self._catchup_buffer = []
            for frame in buffered:
                self._send_frame_locked(frame)
            for txn in self._catchup_system:
                self._send_system_locked(txn)
            self._catchup_system = []
            self.status = ReplicaStatus.READY
        if self.mode is ReplicationMode.ASYNC:
            self._worker = threading.Thread(target=self._drain_loop,
                                            daemon=True)
            self._worker.start()

    def _snapshot_bytes(self) -> bytes:
        import os
        import tempfile
        from ..storage.durability.snapshot import create_snapshot
        if self.storage.config.durability_dir:
            path = create_snapshot(self.storage)
            with open(path, "rb") as f:
                return f.read()
        # no durability dir: snapshot into a temp dir
        from ..storage.storage import StorageConfig
        old = self.storage.config.durability_dir
        with tempfile.TemporaryDirectory() as tmp:
            self.storage.config.durability_dir = tmp
            try:
                path = create_snapshot(self.storage)
                with open(path, "rb") as f:
                    return f.read()
            finally:
                self.storage.config.durability_dir = old

    # --- unified failure / health bookkeeping -------------------------------

    def _mark_failed(self, op: str, exc: BaseException) -> None:
        """One handler for every RPC failure site: count it, mark the
        client INVALID (the heartbeat loop reconnects with backoff), and
        export health so operators see it without grepping logs."""
        from ..utils.sanitize import shared_write
        with self._health_lock:
            shared_write(self, "failures")
            self.failures += 1
            streak = self.failures
        self.status = ReplicaStatus.INVALID
        global_metrics.increment("replication.rpc_failures")
        global_metrics.set_gauge(
            f"replication.replica_health.{self.name}", 0.0)
        log.warning("replica %s %s failed (%d consecutive): %s",
                    self.name, op, streak, exc)

    def _note_ack(self, last_commit_ts: int) -> None:
        """Every successful ack resets the failure streak and refreshes
        the exported lag/health gauges."""
        from ..utils.sanitize import shared_write
        with self._health_lock:
            shared_write(self, "last_acked_ts")
            self.last_acked_ts = last_commit_ts
            self.failures = 0
            self._reconnect_attempts = 0
            self._next_reconnect_at = 0.0
        lag = max(0, self.storage.latest_commit_ts() - last_commit_ts)
        global_metrics.set_gauge(
            f"replication.replica_lag.{self.name}", float(lag))
        global_metrics.set_gauge(
            f"replication.replica_health.{self.name}", 1.0)

    def acked_ts(self) -> int:
        """last_acked_ts under the health lock (SHOW REPLICAS, tests)."""
        from ..utils.sanitize import shared_read
        with self._health_lock:
            shared_read(self, "last_acked_ts")
            return self.last_acked_ts

    def _set_acked(self, last_commit_ts: int) -> None:
        from ..utils.sanitize import shared_write
        with self._health_lock:
            shared_write(self, "last_acked_ts")
            self.last_acked_ts = last_commit_ts

    def reconnect_due(self, now: float) -> bool:
        from ..utils.sanitize import shared_read
        with self._health_lock:
            shared_read(self, "_next_reconnect_at")
            return now >= self._next_reconnect_at

    def note_reconnect_attempt(self, ok: bool) -> bool:
        """Record a reconnect outcome; returns True when this was the
        FIRST failure of the current outage (callers log that one at
        WARNING and the backed-off retries at DEBUG)."""
        from ..utils.sanitize import shared_write
        with self._health_lock:
            shared_write(self, "_reconnect_attempts")
            if ok:
                self._reconnect_attempts = 0
                self._next_reconnect_at = 0.0
                return False
            first = self._reconnect_attempts == 0
            delay = self.retry_policy.delay_for(
                min(self._reconnect_attempts,
                    self.retry_policy.max_retries))
            self._reconnect_attempts += 1
            self._next_reconnect_at = time.monotonic() + delay
            return first

    def retry_budget_exhausted(self) -> bool:
        """True once failures + backoff reconnect attempts blow past the
        policy budget — the trigger for STRICT_SYNC degradation."""
        from ..utils.sanitize import shared_read
        with self._health_lock:
            shared_read(self, "failures")
            return (self.failures + self._reconnect_attempts
                    > self.retry_policy.max_retries)

    # --- commit shipping ----------------------------------------------------

    def ship(self, frame: bytes) -> bool:
        """Ship one commit frame per the replica's mode. Returns success."""
        if self.status is ReplicaStatus.INVALID:
            return False
        with self._lock:
            if self.status is ReplicaStatus.RECOVERY:
                self._catchup_buffer.append(frame)
                return True
        if self.mode is ReplicationMode.ASYNC:
            try:
                self._queue.put_nowait(frame)
                return True
            except queue.Full:
                log.warning("replica %s queue full; marking invalid",
                            self.name)
                self.status = ReplicaStatus.INVALID
                return False
        return self._send_frame_sync(frame)

    def _send_frame_sync(self, frame: bytes) -> bool:
        with self._lock:
            return self._send_frame_locked(frame)

    def enqueue_system(self, txn: dict) -> None:
        """Queue a system txn in seq order (called under the state lock)."""
        with self._syslock:
            self._system_queue.append(txn)

    def drain_system(self) -> None:
        """Deliver queued system txns in order. Only one drainer runs at a
        time per client, so deliveries never interleave out of seq order."""
        with self._syslock:
            if self._sys_draining:
                return
            self._sys_draining = True
        try:
            while True:
                with self._syslock:
                    if not self._system_queue:
                        # clear the flag in the SAME critical section that
                        # observes the queue empty: a txn enqueued after an
                        # unlocked empty-check but before a finally-block
                        # clear would see _sys_draining=True and never be
                        # delivered (lost wakeup)
                        self._sys_draining = False
                        return
                    txn = self._system_queue.pop(0)
                self.send_system(txn)
        except BaseException:
            with self._syslock:
                self._sys_draining = False
            raise

    def send_system(self, txn: dict) -> bool:
        with self._lock:
            if self.status is ReplicaStatus.RECOVERY:
                # published mid-catch-up: the full dump may have been built
                # before this txn; buffer it to drain before going live
                self._catchup_system.append(txn)
                return True
            return self._send_system_locked(txn)

    def _send_system_locked(self, txn: dict) -> bool:
        try:
            carrier = mgtrace.inject()
            if carrier is not None:
                # system txns are JSON: the replication wire carries the
                # trace context so the replica-side apply span joins the
                # originating query's trace
                txn = {**txn, "trace": carrier}
            if FI.fire("repl.send") == "drop":
                raise FI.FaultInjected("injected drop of system txn")
            self._net_out()
            P.send_json(self._sock, P.MSG_SYSTEM, txn)
            msg_type, _ = P.recv_frame(self._sock)
            self._net_in()
            return msg_type == P.MSG_ACK
        except (ConnectionError, OSError) as e:
            self._mark_failed("system txn", e)
            return False

    def _send_frame_locked(self, frame: bytes) -> bool:
        t0 = time.perf_counter()
        try:
            # one span per (frame, replica): the replication-ack leg of
            # a committing query's trace
            with mgtrace.span("repl.ship") as sp:
                if sp:
                    sp.set(replica=self.name, bytes=len(frame))
                if FI.fire("repl.send") == "drop":
                    # the frame is lost before hitting the wire; the ack
                    # timeout/reconnect path must re-ship it via catch-up
                    raise FI.FaultInjected("injected drop of WAL frame")
                self._net_out()
                P.send_frame(self._sock, P.MSG_WAL_FRAME, frame)
                msg_type, payload = P.recv_frame(self._sock)
                self._net_in()
                if msg_type == P.MSG_ACK:
                    self._note_ack(
                        P.parse_json(payload)["last_commit_ts"])
                    global_metrics.observe(
                        "replication.ship_latency_sec",
                        time.perf_counter() - t0)
                    return True
            self._mark_failed("frame ship", ValueError(f"nack {msg_type}"))
            return False
        except (ConnectionError, OSError) as e:
            self._mark_failed("frame ship", e)
            return False

    # --- 2PC (STRICT_SYNC) --------------------------------------------------

    # 2PC vote RPCs run inside the storage engine lock — a hung replica
    # there stalls every new transaction, so they get a short dedicated
    # timeout instead of the 30s connection default (advisor finding).
    TWO_PC_RPC_TIMEOUT_SEC = 5.0

    def prepare(self, frame: bytes) -> bool:
        """Phase 1: ship the frame for a vote (held pending on the replica)."""
        if self.status is not ReplicaStatus.READY:
            return False
        with self._lock:
            try:
                if self._sock is None:
                    return False
                old = self._sock.gettimeout()
                self._sock.settimeout(self.TWO_PC_RPC_TIMEOUT_SEC)
                try:
                    if FI.fire("repl.send") == "drop":
                        raise FI.FaultInjected("injected drop of prepare")
                    self._net_out()
                    P.send_frame(self._sock, P.MSG_PREPARE, frame)
                    msg_type, payload = P.recv_frame(self._sock)
                    self._net_in()
                finally:
                    self._sock.settimeout(old)
                return msg_type == P.MSG_ACK
            except (ConnectionError, OSError) as e:
                self._mark_failed("prepare", e)
                return False

    def finalize(self, commit_ts: int, decision: str) -> bool:
        """Phase 2: commit/abort the pending frame."""
        with self._lock:
            try:
                if self._sock is None:  # mid-registration: nothing prepared
                    return False
                old = self._sock.gettimeout()
                self._sock.settimeout(self.TWO_PC_RPC_TIMEOUT_SEC)
                try:
                    self._net_out()
                    P.send_json(self._sock, P.MSG_FINALIZE,
                                {"commit_ts": commit_ts, "decision": decision})
                    msg_type, payload = P.recv_frame(self._sock)
                    self._net_in()
                finally:
                    self._sock.settimeout(old)
                if msg_type == P.MSG_ACK:
                    if decision == "commit":
                        self._note_ack(P.parse_json(
                            payload)["last_commit_ts"])
                    return True
                self._mark_failed("finalize", ValueError(f"nack {msg_type}"))
                return False
            except (ConnectionError, OSError) as e:
                self._mark_failed("finalize", e)
            return False

    def _drain_loop(self) -> None:
        while not self._stop.is_set():
            try:
                frame = self._queue.get(timeout=0.5)
            except queue.Empty:
                continue
            self._send_frame_sync(frame)

    def heartbeat(self) -> bool:
        # short timeout: heartbeat holds the per-client lock, and the 2PC
        # commit path (inside the storage engine lock) waits on that same
        # lock — a wedged replica must not stall commits for 30s
        with self._lock:
            try:
                if self._sock is None:
                    return False
                old = self._sock.gettimeout()
                self._sock.settimeout(self.TWO_PC_RPC_TIMEOUT_SEC)
                try:
                    self._net_out()
                    P.send_json(self._sock, P.MSG_HEARTBEAT,
                                {"main_commit_ts":
                                 self.storage.latest_commit_ts()})
                    msg_type, payload = P.recv_frame(self._sock)
                    self._net_in()
                finally:
                    self._sock.settimeout(old)
                if msg_type == P.MSG_ACK:
                    self._note_ack(P.parse_json(payload)["last_commit_ts"])
                    return True
                self._mark_failed("heartbeat",
                                  ValueError(f"nack {msg_type}"))
                return False
            except (ConnectionError, OSError) as e:
                self._mark_failed("heartbeat", e)
            return False

    def close(self) -> None:
        self._stop.set()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass


class ReplicationState:
    """Role + replica registry, owned by the InterpreterContext.

    Reference analog: ReplicationState + ReplicationHandler
    (src/replication/state.hpp, replication_handler.cpp).
    """

    HEARTBEAT_INTERVAL_SEC = 2.0

    def __init__(self, storage, ictx=None, node_name: str | None = None):
        import os as _os
        self.storage = storage
        self.ictx = ictx           # system-state source (auth, dbms)
        self.role = "main"
        # logical node name for the nemesis network model (chaos tests
        # partition links keyed on these names)
        self.node_name = node_name or _os.environ.get(
            "MEMGRAPH_TPU_NODE_NAME", "main")
        # fencing: the promotion epoch this instance last learned from
        # the coordinator (promote/demote RPC) or from a replica's
        # MSG_FENCED refusal. A MAIN that observes a newer epoch than
        # its own has been deposed and must stop acking writes.
        self.fencing_epoch = 0
        self.fenced = False
        # STRICT_SYNC degradation trades safety for availability; a
        # fenced/HA deployment turns it off so a partitioned MAIN can
        # never silently stop waiting for its strict replicas (that is
        # exactly the split-brain ack-loss window)
        self.allow_strict_degradation = True
        self._system_seq = 0
        self.replicas: dict[str, ReplicaClient] = {}
        self.replica_server = None
        self._lock = tracked_lock("ReplicationState._lock")
        self._consumer_registered = False
        # recent-commit ring for the WAL-delta catch-up rung (reference:
        # storage/v2/replication/recovery.hpp ladder): a briefly-behind
        # replica receives just the missed frames instead of a snapshot.
        # _frames_floor = highest commit_ts that may be MISSING from the
        # ring (commits before the consumer registered, or evicted).
        import os as _os
        self._recent_frames: "deque[tuple[int, bytes]]" = deque()
        self._frames_floor = 0
        self._frames_cap = int(_os.environ.get(
            "MEMGRAPH_TPU_REPL_RING", 4096))
        self._frames_lock = tracked_lock("ReplicationState._frames_lock")
        self._heartbeat_thread: threading.Thread | None = None
        self._stop_heartbeat = threading.Event()
        self._reconnecting: set[int] = set()
        from ..utils.sanitize import shared_field
        shared_field(self, "replicas", "_recent_frames", "_frames_floor",
                     "_reconnecting", "_system_seq", "fencing_epoch",
                     "fenced")

    def _ensure_consumer(self) -> None:
        # lazy: commits only pay frame encoding once a replica exists
        if not self._consumer_registered:
            with self._frames_lock:
                # commits made while no consumer ran never reached the
                # ring: everything at/below the current ts needs snapshot
                self._recent_frames.clear()
                self._frames_floor = self.storage.latest_commit_ts()
            self.storage.frame_consumers.append(self._on_commit_frame)
            self.storage.pre_commit_hooks.append(self._on_pre_commit)
            self.storage.commit_abort_hooks.append(self._on_commit_abort)
            self._consumer_registered = True

    def _maybe_remove_consumer(self) -> None:
        # mglint: disable=MG006 — every caller (drop_replica, demote, register failure path) holds self._lock; intraprocedural analysis cannot see it
        if self._consumer_registered and not self.replicas:
            for lst, hook in ((self.storage.frame_consumers,
                               self._on_commit_frame),
                              (self.storage.pre_commit_hooks,
                               self._on_pre_commit),
                              (self.storage.commit_abort_hooks,
                               self._on_commit_abort)):
                try:
                    lst.remove(hook)
                except ValueError:
                    pass
            self._consumer_registered = False

    # --- role management ----------------------------------------------------

    # --- durable state (reference: --replication-restore-state-on-startup,
    # replication/state.hpp persisted role + registry) ----------------------

    def _kv(self):
        return getattr(self.ictx, "kvstore", None) if self.ictx else None

    def _persist_state(self) -> None:
        kv = self._kv()
        if kv is None:
            return
        import json
        with self._lock:
            doc = {"role": self.role,
                   "listen_port": (self.replica_server.port
                                   if self.replica_server else 0),
                   "fencing_epoch": self.fencing_epoch,
                   "replicas": [
                       {"name": r.name, "address": r.address,
                        "mode": r.mode.name}
                       for r in self.replicas.values()]}
        kv.put("replication:state", json.dumps(doc))

    def restore_state(self) -> None:
        """Re-apply the persisted role + replica registry (called at
        startup under --replication-restore-state-on-startup)."""
        kv = self._kv()
        if kv is None:
            return
        import json
        raw = kv.get_str("replication:state")
        if not raw:
            return
        try:
            doc = json.loads(raw)
        except ValueError:
            return
        epoch = int(doc.get("fencing_epoch") or 0)
        if doc.get("role") == "replica" and doc.get("listen_port"):
            self.set_role_replica("0.0.0.0", int(doc["listen_port"]),
                                  epoch=epoch)
            return
        with self._lock:
            self.fencing_epoch = max(self.fencing_epoch, epoch)
        from ..exceptions import QueryException
        for spec in doc.get("replicas", ()):
            try:
                self.register_replica(spec["name"], spec["address"],
                                      ReplicationMode[spec["mode"]])
            except (KeyError, ConnectionError, OSError,
                    QueryException) as e:
                # an unreachable replica must not block startup — it can
                # be re-registered (or will reconnect) later
                log.warning("replication state restore: replica %r not "
                            "restored (%s); re-register it or let the "
                            "heartbeat reconnect it",
                            spec.get("name", "?"), e)
                continue

    def set_role_replica(self, host: str, port: int,
                         epoch: int | None = None) -> None:
        from ..exceptions import QueryException
        from .replica import ReplicaServer
        with self._lock:
            for r in self.replicas.values():
                r.close()
            self.replicas.clear()
            self._maybe_remove_consumer()
            if epoch is not None:
                self.fencing_epoch = max(self.fencing_epoch, int(epoch))
            if self.replica_server is not None:
                self.replica_server.stop()
                self.replica_server = None
            server = ReplicaServer(self.storage, host, port,
                                   ictx=self.ictx,
                                   fencing_epoch=self.fencing_epoch)
            try:
                server.start()
            except OSError as e:
                raise QueryException(
                    f"cannot listen on {host}:{port}: {e}") from e
            self.replica_server = server
            self.role = "replica"
            self.fenced = False    # a demoted node is no longer a main
        self._persist_state()

    def set_role_main(self, epoch: int | None = None) -> None:
        from ..exceptions import FencedException
        with self._lock:
            if epoch is not None and int(epoch) < self.fencing_epoch:
                # a delayed/replayed promote RPC from a PREVIOUS epoch
                # must not resurrect a deposed main
                raise FencedException(
                    f"stale promote epoch {epoch} < known "
                    f"{self.fencing_epoch}")
            server, self.replica_server = self.replica_server, None
        if server is not None:
            # presumed-commit OUTSIDE the state lock (the WAL apply
            # takes the engine lock, whose commit path takes the state
            # lock — holding it here closes a lock cycle): prepared 2PC
            # frames whose finalize never arrived are applied so an
            # acked write on the old MAIN survives this promotion
            server.apply_pending_2pc()
            server.stop()
        with self._lock:
            # re-check under the write lock: a concurrent fence/demote
            # may have advanced the epoch while the 2PC drain ran — a
            # now-stale promote must still be refused (the coordinator's
            # reconcile loop repairs the half-stopped server state)
            if epoch is not None and int(epoch) < self.fencing_epoch:
                raise FencedException(
                    f"stale promote epoch {epoch} < known "
                    f"{self.fencing_epoch} (epoch advanced mid-promote)")
            self.role = "main"
            if epoch is not None:
                self.fencing_epoch = max(self.fencing_epoch, int(epoch))
            self.fenced = False
        self._persist_state()

    def current_epoch(self) -> int:
        """Fencing epoch under the state lock (replica registration,
        mgmt state_check)."""
        from ..utils.sanitize import shared_read
        with self._lock:
            shared_read(self, "fencing_epoch")
            return self.fencing_epoch

    def is_fenced(self) -> bool:
        from ..utils.sanitize import shared_read
        with self._lock:
            shared_read(self, "fenced")
            return self.fenced

    def fencing_info(self) -> tuple[int, bool]:
        """(fencing_epoch, fenced) as one consistent snapshot."""
        from ..utils.sanitize import shared_read
        with self._lock:
            shared_read(self, "fencing_epoch")
            return self.fencing_epoch, self.fenced

    def replica_names(self) -> list[str]:
        """Registered replica names under the state lock (state_check)."""
        with self._lock:
            return sorted(self.replicas)

    def fence(self, observed_epoch: int) -> None:
        """A replica (or the coordinator) proved a newer MAIN exists:
        stop acking writes until promoted again with a fresh epoch."""
        from ..utils.sanitize import shared_write
        with self._lock:
            if observed_epoch <= self.fencing_epoch and self.fenced:
                return
            shared_write(self, "fencing_epoch")
            self.fencing_epoch = max(self.fencing_epoch,
                                     int(observed_epoch))
            self.fenced = True
        global_metrics.increment("replication.fenced_total")
        log.error(
            "MAIN %s FENCED: epoch %d superseded ours — refusing further "
            "write acks until re-promoted", self.node_name, observed_epoch)

    def shutdown(self) -> None:
        """Hard-stop everything this state owns (chaos kill / dbms
        teardown): heartbeat loop, replica clients, replica server."""
        self._stop_heartbeat.set()
        with self._lock:
            clients = list(self.replicas.values())
            server, self.replica_server = self.replica_server, None
        for c in clients:
            c.close()
        if server is not None:
            server.stop()

    # --- replica registry ---------------------------------------------------

    def register_replica(self, name: str, address: str,
                         mode: ReplicationMode) -> None:
        from ..exceptions import FencedException, QueryException
        if self.role != "main":
            raise QueryException("only MAIN can register replicas")
        if self.is_fenced():
            raise FencedException(
                "this MAIN is fenced (a newer epoch exists); it cannot "
                "adopt replicas")
        client = ReplicaClient(name, address, mode, self.storage,
                               src_node=self.node_name,
                               epoch_fn=self.current_epoch)
        client.system_state_provider = self.system_state
        client.recent_frames_provider = self._frames_since
        with self._lock:
            if name in self.replicas:
                raise QueryException(f"replica {name!r} already registered")
            # visible to the commit path BEFORE catch-up starts: frames
            # committed during the snapshot transfer buffer on the client
            # (status RECOVERY) and drain after it — no gap
            self.replicas[name] = client
            self._ensure_consumer()
        try:
            client.connect_and_catch_up()
        except (ConnectionError, OSError, QueryException) as e:
            with self._lock:
                # re-validate under the lock: a concurrent drop+register
                # may have installed a DIFFERENT client under this name
                # while catch-up ran — only unregister our own (MG007)
                if self.replicas.get(name) is client:
                    del self.replicas[name]
                self._maybe_remove_consumer()
            client.close()
            if isinstance(e, FencedError):
                # the replica proved a newer MAIN exists: fence NOW so
                # no further commit on this deposed main gets acked
                self.fence(e.observed_epoch)
                raise FencedException(str(e)) from e
            raise QueryException(
                f"cannot register replica {name!r}: {e}") from e
        self._persist_state()
        self._start_heartbeat()

    def drop_replica(self, name: str) -> None:
        from ..exceptions import QueryException
        with self._lock:
            client = self.replicas.pop(name, None)
            self._maybe_remove_consumer()
        if client is None:
            raise QueryException(f"replica {name!r} is not registered")
        client.close()
        self._persist_state()

    # --- liveness -----------------------------------------------------------

    def _start_heartbeat(self) -> None:
        if self._heartbeat_thread is not None:
            return
        self._heartbeat_thread = threading.Thread(
            target=self._heartbeat_loop, daemon=True)
        self._heartbeat_thread.start()

    def _heartbeat_loop(self) -> None:
        while not self._stop_heartbeat.wait(self.HEARTBEAT_INTERVAL_SEC):
            with self._lock:
                clients = list(self.replicas.values())
            for c in clients:
                if c.status is ReplicaStatus.READY:
                    c.heartbeat()
                elif c.status is ReplicaStatus.INVALID and \
                        c.reconnect_due(time.monotonic()):
                    # auto-reconnect on a per-replica worker thread: one
                    # dead replica's connect timeout or long snapshot
                    # transfer must not stall heartbeats to the others;
                    # attempts back off per the client's RetryPolicy
                    self._spawn_reconnect(c)

    def _spawn_reconnect(self, client) -> None:
        name = client.name
        # dedup by client identity, not name: a stale worker for a dropped
        # client must not block reconnects of a re-registered replacement
        key = id(client)
        with self._lock:
            if key in self._reconnecting:
                return
            self._reconnecting.add(key)

        def run():
            # Catch EVERYTHING: one malformed ack must not kill the
            # worker silently mid-bookkeeping (reference: the
            # replication client's retry loop); the WAL-delta rung makes
            # this cheap for briefly-severed replicas.
            try:
                # ownership check BEFORE connecting: a dropped/demoted
                # replica must not receive a snapshot from a main that no
                # longer owns it
                with self._lock:
                    if self.replicas.get(name) is not client:
                        return
                try:
                    client.connect_and_catch_up()
                except FencedError as fe:
                    # the replica now answers to a newer MAIN; stop
                    # reconnecting AND stop acking — we are deposed
                    self.fence(fe.observed_epoch)
                    return
                # re-check: drop may have raced the transfer — don't
                # resurrect a connection the registry no longer owns
                with self._lock:
                    still_ours = self.replicas.get(name) is client
                if not still_ours:
                    client.close()
                else:
                    client.note_reconnect_attempt(True)
                    log.info("replica %s reconnected via %s catch-up",
                             client.name, client.catchup_used)
            except Exception:
                # the streak read + bump is one atomic step inside the
                # client's health lock (mgsan: the old read-then-bump
                # raced other workers into duplicate WARNINGs)
                first = client.note_reconnect_attempt(False)
                # WARNING once per outage (the operator-visible event),
                # debug for the backed-off retries — a dead replica must
                # not spam one warning per attempt forever
                if first:
                    log.warning("replica %s reconnect failed; retrying "
                                "with backoff", client.name,
                                exc_info=True)
                else:
                    log.debug("replica %s reconnect failed again",
                              client.name, exc_info=True)
            finally:
                with self._lock:
                    self._reconnecting.discard(key)

        threading.Thread(target=run, daemon=True,
                         name=f"repl-reconnect-{name}").start()

    def show_replicas(self) -> list[list]:
        rows = []
        with self._lock:
            clients = list(self.replicas.values())
        for c in clients:
            rows.append([c.name, c.address, c.mode.value,
                         c.acked_ts(), c.status.value])
        return rows

    # --- system-state replication -------------------------------------------

    def system_state(self) -> dict:
        """Full system state for catch-up: auth dump + database names
        (reference: the system txn log replayed at replica registration,
        src/system/transaction.cpp)."""
        out = {}
        ictx = self.ictx
        if ictx is not None:
            auth = getattr(ictx, "auth_store", None)
            if auth is not None:
                out["auth"] = auth.to_dict()
            dbms = getattr(ictx, "dbms", None)
            if dbms is not None:
                out["databases"] = dbms.names()
        return out

    def publish_system(self, kind: str, data: dict) -> None:
        """Ship one ordered system transaction to every replica. Best
        effort per replica (a failed replica is marked INVALID and will
        receive the full state on re-registration)."""
        if self.role != "main":
            return
        # seq assignment + per-client enqueue under the state lock (fixes
        # global ordering); DELIVERY happens outside it via each client's
        # ordered drain — a wedged replica must not stall data commits,
        # which also contend on this lock (_on_pre_commit)
        with self._lock:
            self._system_seq += 1
            txn = {"seq": self._system_seq, "kind": kind, "data": data}
            clients = []
            for c in self.replicas.values():
                if c.status in (ReplicaStatus.READY, ReplicaStatus.RECOVERY):
                    c.enqueue_system(txn)
                    clients.append(c)
        for c in clients:
            c.drain_system()

    # --- commit hook --------------------------------------------------------

    def _on_pre_commit(self, frame: bytes, commit_ts: int) -> None:
        """2PC phase 1 (under the engine lock, before WAL + visibility):
        every STRICT_SYNC replica must vote yes or the commit aborts
        (reference: PrepareCommit with vote wait,
        inmemory/storage.cpp:1224-1272)."""
        if self.role != "main":
            return
        epoch, fenced = self.fencing_info()
        if fenced:
            # refused BEFORE any prepare: a deposed main acks nothing
            from ..exceptions import FencedException
            raise FencedException(
                f"write refused: this MAIN is fenced (epoch "
                f"{epoch} superseded it)")
        with self._lock:
            all_strict = [c for c in self.replicas.values()
                          if c.mode is ReplicationMode.STRICT_SYNC]
        # a STRICT_SYNC replica that cannot vote means NO commit may
        # proceed — that is the strict guarantee. RECOVERY counts as
        # unavailable too: with heartbeat auto-reconnect a replica can sit
        # mid-catch-up at commit time, and if that catch-up fails a
        # buffered frame would be silently lost after MAIN committed.
        # Graceful degradation: a replica that has already exhausted its
        # retry budget is DEMOTED to ASYNC catch-up instead of wedging
        # every future commit (loud metric + log; catch-up re-ships what
        # it missed once it returns).
        down = [c for c in all_strict if c.status is not ReplicaStatus.READY]
        still_down = []
        for c in down:
            if self.allow_strict_degradation and \
                    c.retry_budget_exhausted():
                self._demote_strict(c)
            else:
                still_down.append(c)
        if still_down:
            # ReplicaUnavailable (not the generic TransactionException):
            # nothing was prepared anywhere, so this abort is a SAFE
            # "definitely did not happen" — chaos clients rely on that
            from ..exceptions import ReplicaUnavailableException
            raise ReplicaUnavailableException(
                "STRICT_SYNC replica(s) unavailable: "
                + ", ".join(c.name for c in still_down)
                + " — transaction aborted (drop the replica or restore it)")
        # every remaining strict client is READY here (the vote above
        # aborts otherwise; demoted clients left the strict set)
        strict = [c for c in all_strict
                  if c.mode is ReplicationMode.STRICT_SYNC]
        if not strict:
            return
        prepared = []
        failed = []
        for c in strict:
            if c.prepare(frame):
                prepared.append(c)
            else:
                failed.append(c)
        if failed:
            for c in prepared:
                c.finalize(commit_ts, "abort")
            from ..exceptions import TransactionException
            raise TransactionException(
                "STRICT_SYNC replica(s) did not confirm the prepare phase: "
                + ", ".join(c.name for c in failed)
                + " — transaction aborted")

    def _demote_strict(self, client) -> None:
        """STRICT_SYNC → ASYNC-catchup degradation: acknowledged commits
        stop waiting for a replica that exhausted its retry budget. Loud
        by design — an operator must notice the durability downgrade."""
        client.mode = ReplicationMode.ASYNC
        client.degraded_from_strict = True
        global_metrics.increment("replication.strict_sync_demotions")
        global_metrics.set_gauge(
            f"replication.replica_degraded.{client.name}", 1.0)
        log.error(
            "STRICT_SYNC replica %s exhausted its retry budget "
            "(max_retries=%d) — DEMOTED to ASYNC catch-up; commits no "
            "longer wait for its vote (re-register to restore strictness)",
            client.name, client.retry_policy.max_retries)

    def _on_commit_abort(self, commit_ts: int) -> None:
        """Commit failed after the 2PC vote succeeded (e.g. the WAL write
        raised): release the prepared frame on every STRICT_SYNC replica
        so it is not orphaned in its pending-2PC table forever."""
        if self.role != "main":
            return
        # filter by mode only, NOT by READY: a replica that voted yes may
        # have been marked INVALID concurrently (heartbeat thread); sending
        # abort to an un-prepared replica is harmless (it pops nothing)
        with self._lock:
            strict = [c for c in self.replicas.values()
                      if c.mode is ReplicationMode.STRICT_SYNC]
        for c in strict:
            try:
                c.finalize(commit_ts, "abort")
            except Exception:
                # one broken client must not keep the abort from the rest
                log.exception("finalize(abort) failed for replica %s", c.name)

    def _frames_since(self, since_ts: int):
        """WAL frames with commit_ts > since_ts in commit order, or None
        when the ring no longer covers that range (snapshot needed)."""
        from ..utils.sanitize import shared_read
        with self._frames_lock:
            shared_read(self, "_recent_frames")
            if since_ts < self._frames_floor:
                return None
            return [f for ts, f in self._recent_frames if ts > since_ts]

    def _on_commit_frame(self, frame: bytes, commit_ts: int) -> None:
        if self.role != "main":
            return
        from ..utils.sanitize import shared_write
        with self._frames_lock:
            shared_write(self, "_recent_frames")
            self._recent_frames.append((commit_ts, frame))
            while len(self._recent_frames) > self._frames_cap:
                ts, _ = self._recent_frames.popleft()
                if ts > self._frames_floor:
                    self._frames_floor = ts
        with self._lock:
            clients = list(self.replicas.values())
        if not clients:
            return
        for c in clients:
            if c.mode is ReplicationMode.STRICT_SYNC:
                if c.status is ReplicaStatus.READY:
                    # 2PC phase 2: the frame was prepared pre-visibility
                    c.finalize(commit_ts, "commit")
                elif c.status is ReplicaStatus.RECOVERY:
                    c.ship(frame)  # buffers for the catch-up drain
                continue
            ok = c.ship(frame)
            if not ok and c.mode is ReplicationMode.SYNC:
                # the commit is already locally visible — raising here could
                # only corrupt the session; the replica is marked INVALID and
                # surfaces through SHOW REPLICAS
                log.error("replica %s (sync) failed to confirm commit %d",
                          c.name, commit_ts)
