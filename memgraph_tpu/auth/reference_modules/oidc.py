#!/usr/bin/env python3
"""OIDC SSO reference module (subprocess JSON-line protocol).

Validates OIDC access/ID tokens (RS256 JWTs) against the identity
provider's JWKS endpoint and maps IdP roles to local roles. Behavior
mirrors the reference's OIDC module
(/root/reference/src/auth/reference_modules/oidc.py: scheme variants
oidc-entra-id / oidc-okta / oidc-custom, env-driven config including the
MEMGRAPH_SSO_* variable names, "token_type:field" username selection,
"idp_role:role1,role2;..." role mappings) — reimplemented on the stdlib
+ `cryptography` (no PyJWT/requests in this image) and on THIS repo's
module protocol: one JSON line {"scheme", "username", "response"} in,
one JSON line {"authenticated", "username", "roles"} out.

The Bolt client supplies `response` as "access_token=...;id_token=..."
(the reference's convention). JWKS endpoints may be http(s):// or
file:// — the latter lets tests and air-gapped deployments pin keys.
"""

from __future__ import annotations

import base64
import json
import os
import sys
import time
import urllib.request


def _b64url(data: str) -> bytes:
    pad = -len(data) % 4
    return base64.urlsafe_b64decode(data + "=" * pad)


def _b64url_uint(data: str) -> int:
    return int.from_bytes(_b64url(data), "big")


_JWKS_CACHE: dict = {}          # url -> (fetched_at, jwks)
JWKS_TTL_SEC = 300.0            # IdPs rate-limit their keys endpoints


def _fetch_jwks(url: str, cafile=None) -> dict:
    cached = _JWKS_CACHE.get(url)
    if cached and time.time() - cached[0] < JWKS_TTL_SEC:
        return cached[1]
    ctx = None
    if url.startswith("https"):
        import ssl
        ctx = ssl.create_default_context(cafile=cafile)
    with urllib.request.urlopen(url, timeout=10, context=ctx) as resp:
        jwks = json.loads(resp.read().decode("utf-8"))
    _JWKS_CACHE[url] = (time.time(), jwks)
    return jwks


def _verify_rs256(token: str, jwk: dict) -> dict:
    """Verify header.payload signature against an RSA JWK; returns the
    decoded claims. Raises ValueError on any failure."""
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import padding, rsa

    head_b64, body_b64, sig_b64 = token.split(".")
    pub = rsa.RSAPublicNumbers(
        _b64url_uint(jwk["e"]), _b64url_uint(jwk["n"])).public_key()
    try:
        pub.verify(_b64url(sig_b64),
                   f"{head_b64}.{body_b64}".encode("ascii"),
                   padding.PKCS1v15(), hashes.SHA256())
    except Exception as e:  # noqa: BLE001 — any crypto failure = invalid
        raise ValueError(f"signature verification failed: {e}") from e
    return json.loads(_b64url(body_b64))


def validate_jwt(token: str, jwks: dict, audience: str | None) -> dict:
    """Full token validation: alg, kid lookup, signature, exp, aud."""
    try:
        header = json.loads(_b64url(token.split(".")[0]))
    except Exception as e:  # noqa: BLE001
        raise ValueError(f"cannot decode JWT header: {e}") from e
    if header.get("alg") != "RS256":
        raise ValueError("invalid algorithm in header (RS256 required)")
    kid = header.get("kid")
    if not kid:
        raise ValueError("missing key ID (kid) in JWT header")
    keys = jwks.get("keys")
    if not isinstance(keys, list):
        raise ValueError("invalid JWKS response: missing keys array")
    jwk = next((k for k in keys if k.get("kid") == kid), None)
    if jwk is None:
        raise ValueError("matching kid not found")
    claims = _verify_rs256(token, jwk)
    exp = claims.get("exp")
    if exp is None:
        raise ValueError("token missing expiration claim")
    if int(exp) < int(time.time()):
        raise ValueError("token expired")
    nbf = claims.get("nbf")
    if nbf is not None and int(nbf) > int(time.time()):
        raise ValueError("token not yet valid")
    if audience:
        aud = claims.get("aud")
        auds = aud if isinstance(aud, list) else [aud]
        if audience not in auds:
            raise ValueError("audience mismatch")
    return claims


def parse_role_mappings(raw: str) -> dict:
    """'idp_role:role1,role2;other:role3' -> {idp_role: [roles...]}."""
    out: dict[str, list] = {}
    if not raw or not raw.strip():
        raise ValueError("missing role mappings")
    for mapping in raw.strip().split(";"):
        if not mapping.strip():
            continue
        parts = mapping.split(":")
        if len(parts) != 2:
            raise ValueError(f"invalid role mapping: {mapping}")
        roles = [r.strip() for r in parts[1].split(",") if r.strip()]
        if not roles:
            raise ValueError(f"no valid roles specified for: {parts[0]}")
        out[parts[0].strip()] = roles
    return out


_SCHEME_PREFIX = {
    "oidc-entra-id": "MEMGRAPH_SSO_ENTRA_ID_OIDC",
    "oidc-okta": "MEMGRAPH_SSO_OKTA_OIDC",
    "oidc-custom": "MEMGRAPH_SSO_CUSTOM_OIDC",
}


def load_config(scheme: str) -> dict:
    p = _SCHEME_PREFIX[scheme]
    env = os.environ.get
    cfg = {
        "role_field": env(f"{p}_ROLE_FIELD",
                          "groups" if scheme == "oidc-okta" else "roles"),
        "username": env(f"{p}_USERNAME", "id:sub"),
        "role_mapping": parse_role_mappings(env(f"{p}_ROLE_MAPPING", "")),
        "cafile": env(f"{p}_EXTRA_CA_CERTS") or None,
    }
    if scheme == "oidc-entra-id":
        tenant = env(f"{p}_TENANT_ID", "")
        cfg["jwks_uri"] = (f"https://login.microsoftonline.com/{tenant}"
                           "/discovery/v2.0/keys")
        cfg["access_aud"] = cfg["id_aud"] = env(f"{p}_CLIENT_ID", "")
    elif scheme == "oidc-okta":
        cfg["jwks_uri"] = f"{env(f'{p}_ISSUER', '')}/v1/keys"
        cfg["access_aud"] = env(f"{p}_AUTHORIZATION_SERVER", "")
        cfg["id_aud"] = env(f"{p}_CLIENT_ID", "")
    else:
        cfg["jwks_uri"] = env(f"{p}_PUBLIC_KEY_ENDPOINT", "")
        cfg["access_aud"] = env(f"{p}_ACCESS_TOKEN_AUDIENCE", "")
        cfg["id_aud"] = env(f"{p}_ID_TOKEN_AUDIENCE", "")
    cfg["use_id_token"] = cfg["username"].startswith("id:")
    return cfg


def map_roles(claims: dict, cfg: dict) -> list:
    field = cfg["role_field"]
    if field not in claims:
        raise ValueError(
            f"missing roles field named {field} — roles are probably not "
            "configured on the token issuer")
    idp_roles = claims[field]
    if isinstance(idp_roles, str):
        idp_roles = [idp_roles]
    matched: list = []
    for r in idp_roles:
        for local in cfg["role_mapping"].get(r, ()):
            if local not in matched:
                matched.append(local)
    if not matched:
        raise ValueError(
            f"cannot map any of the roles {sorted(idp_roles)} to local roles")
    return matched


def authenticate(scheme: str, response: str) -> dict:
    if scheme not in _SCHEME_PREFIX:
        return {"authenticated": False, "errors": "invalid SSO scheme"}
    try:
        cfg = load_config(scheme)
        tokens = dict(t.split("=", 1) for t in response.split(";") if t)
        jwks = _fetch_jwks(cfg["jwks_uri"], cafile=cfg["cafile"])

        def _validate_with_rotation(token, aud):
            """On a kid miss, bypass the JWKS cache once: the IdP may
            have rotated its signing keys inside the cache TTL."""
            nonlocal jwks
            try:
                return validate_jwt(token, jwks, aud)
            except ValueError as e:
                if "kid not found" not in str(e):
                    raise
                _JWKS_CACHE.pop(cfg["jwks_uri"], None)
                jwks = _fetch_jwks(cfg["jwks_uri"], cafile=cfg["cafile"])
                return validate_jwt(token, jwks, aud)

        access = _validate_with_rotation(tokens["access_token"],
                                         cfg["access_aud"] or None)
        id_claims = None
        if cfg["use_id_token"]:
            id_claims = _validate_with_rotation(tokens["id_token"],
                                                cfg["id_aud"] or None)
        roles = map_roles(access, cfg)
        token_type, _, field = cfg["username"].partition(":")
        source = id_claims if token_type == "id" else access
        if not field or source is None or field not in source:
            raise ValueError(f"field {field!r} missing in {token_type} token")
        return {"authenticated": True, "username": str(source[field]),
                "roles": roles}
    except Exception as e:  # noqa: BLE001 — the host treats errors as deny
        return {"authenticated": False, "errors": str(e)}


def main() -> None:
    # stateless loop: one JSON line in, one out (auth/module.py protocol)
    for line in sys.stdin:
        if not line.strip():
            continue
        try:
            params = json.loads(line)
            ret = authenticate(params.get("scheme", ""),
                               params.get("response", ""))
        except Exception as e:  # noqa: BLE001
            ret = {"authenticated": False, "errors": str(e)}
        sys.stdout.write(json.dumps(ret) + "\n")
        sys.stdout.flush()


if __name__ == "__main__":
    main()
