#!/usr/bin/env python3
"""Reference auth module: LDAP bind + role lookup.

Counterpart of /root/reference/src/auth/reference_modules/ldap.py: binds
as the user DN (prefix + username + suffix), optionally resolves a role
from a group search. Config via LDAP_CONFIG env var (JSON):
{"host", "port", "prefix", "suffix", "role_base": optional,
 "role_attribute": optional}. Requires the ldap3 client library.
"""

import json
import os
import sys


def main():
    cfg = json.loads(os.environ.get("LDAP_CONFIG", "{}"))
    try:
        import ldap3
    except ImportError:
        # no client library: deny everything, loudly once
        sys.stderr.write("ldap3 is not installed\n")
        for _ in sys.stdin:
            sys.stdout.write(json.dumps({"authenticated": False}) + "\n")
            sys.stdout.flush()
        return
    server = ldap3.Server(cfg.get("host", "localhost"),
                          port=int(cfg.get("port", 389)))
    for line in sys.stdin:
        reply = {"authenticated": False}
        try:
            req = json.loads(line)
            username = req.get("username", "")
            password = req.get("response", "")
            # empty password would perform an ANONYMOUS bind, which most
            # LDAP servers accept — deny before binding
            if username and password:
                dn = cfg.get("prefix", "") + \
                    ldap3.utils.dn.escape_rdn(username) + \
                    cfg.get("suffix", "")
                conn = ldap3.Connection(server, dn, password)
                if conn.bind():
                    reply = {"authenticated": True, "username": username}
                    base = cfg.get("role_base")
                    if base and conn.search(
                            base, f"(member={dn})",
                            attributes=[cfg.get("role_attribute", "cn")]):
                        if conn.entries:
                            reply["role"] = str(
                                conn.entries[0][
                                    cfg.get("role_attribute", "cn")])
                    conn.unbind()
        except Exception as e:  # noqa: BLE001
            reply = {"authenticated": False, "errors": str(e)}
        sys.stdout.write(json.dumps(reply) + "\n")
        sys.stdout.flush()


if __name__ == "__main__":
    main()
