#!/usr/bin/env python3
"""SAML SSO reference module (subprocess JSON-line protocol).

Validates a base64-encoded SAML Response (saml-entra-id / saml-okta
schemes), verifies the XML signature against the IdP certificate, checks
assertion conditions (NotBefore / NotOnOrAfter / audience), extracts the
NameID or a username attribute plus the role attribute, and maps the IdP
role through MEMGRAPH_SSO_<SCHEME>_SAML_ROLE_MAPPING. The env-variable
surface mirrors the reference module
(/root/reference/src/auth/reference_modules/saml.py: IDP_CERT, IDP_ID,
ASSERTION_AUDIENCE, USE_NAME_ID, USERNAME_ATTRIBUTE, ROLE_MAPPING,
OKTA ROLE_ATTRIBUTE; Entra's role claim URI).

Signature verification deviates deliberately: the reference delegates to
python3-saml/xmlsec (exclusive C14N 1.0) which is not in this image;
this module verifies RSA-SHA256 enveloped signatures using stdlib
`xml.etree.ElementTree.canonicalize` (W3C C14N 2.0) + `cryptography`.
IdPs that sign with exclusive-c14n-1.0 output that differs from C14N
2.0 canonical form are rejected rather than mis-accepted — verification
remains fail-closed.
"""

from __future__ import annotations

import base64
import hashlib
import json
import os
import sys
from datetime import datetime, timezone
from xml.etree import ElementTree as ET

NS = {
    "samlp": "urn:oasis:names:tc:SAML:2.0:protocol",
    "saml": "urn:oasis:names:tc:SAML:2.0:assertion",
    "ds": "http://www.w3.org/2000/09/xmldsig#",
}
ENTRA_ROLE_ATTR = ("http://schemas.microsoft.com/ws/2008/06/identity/"
                   "claims/role")
RSA_SHA256 = "http://www.w3.org/2001/04/xmldsig-more#rsa-sha256"
SHA256_URI = "http://www.w3.org/2001/04/xmlenc#sha256"


def _c14n(element: ET.Element) -> bytes:
    # rewrite_prefixes: digests must not depend on the namespace-prefix
    # names the producer happened to serialize with
    return ET.canonicalize(ET.tostring(element, encoding="unicode"),
                           strip_text=False,
                           rewrite_prefixes=True).encode("utf-8")


def _strip_signatures(element: ET.Element) -> ET.Element:
    """Copy of the tree with ds:Signature elements removed (enveloped-
    signature transform)."""
    clone = ET.fromstring(ET.tostring(element))
    for parent in clone.iter():
        for child in list(parent):
            if child.tag == f"{{{NS['ds']}}}Signature":
                parent.remove(child)
    return clone


def _load_idp_cert(path: str):
    from cryptography import x509
    with open(path, "rb") as f:
        data = f.read()
    if b"BEGIN CERTIFICATE" in data:
        return x509.load_pem_x509_certificate(data).public_key()
    from cryptography.hazmat.primitives.serialization import (
        load_pem_public_key)
    return load_pem_public_key(data)


def verify_signature(root: ET.Element, signed_el: ET.Element,
                     public_key) -> None:
    """Verify the enveloped RSA-SHA256 signature covering signed_el."""
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import padding

    sig = signed_el.find("ds:Signature", NS) or root.find(
        ".//ds:Signature", NS)
    if sig is None:
        raise ValueError("response is not signed")
    signed_info = sig.find("ds:SignedInfo", NS)
    method = sig.find(".//ds:SignatureMethod", NS)
    if signed_info is None or method is None:
        raise ValueError("malformed signature element")
    if method.get("Algorithm") != RSA_SHA256:
        raise ValueError("unsupported signature algorithm (rsa-sha256 only)")
    digest_method = sig.find(".//ds:DigestMethod", NS)
    if digest_method is None or digest_method.get("Algorithm") != SHA256_URI:
        raise ValueError("unsupported digest algorithm (sha256 only)")

    # 1. reference digest: sha256 of the signed element, signatures removed
    digest_value = sig.find(".//ds:DigestValue", NS)
    if digest_value is None or not digest_value.text:
        raise ValueError("missing digest value")
    computed = hashlib.sha256(_c14n(_strip_signatures(signed_el))).digest()
    if base64.b64decode(digest_value.text.strip()) != computed:
        raise ValueError("assertion digest mismatch")

    # 2. signature over canonicalized SignedInfo
    sig_value = sig.find("ds:SignatureValue", NS)
    if sig_value is None or not sig_value.text:
        raise ValueError("missing signature value")
    public_key.verify(base64.b64decode(sig_value.text.strip()),
                      _c14n(signed_info),
                      padding.PKCS1v15(), hashes.SHA256())


def _check_conditions(assertion: ET.Element, audience: str) -> None:
    cond = assertion.find("saml:Conditions", NS)
    if cond is None:
        raise ValueError("assertion has no Conditions")
    now = datetime.now(timezone.utc)

    def parse(ts):
        return datetime.fromisoformat(ts.replace("Z", "+00:00"))

    nb, noa = cond.get("NotBefore"), cond.get("NotOnOrAfter")
    if nb and now < parse(nb):
        raise ValueError("assertion not yet valid")
    if noa and now >= parse(noa):
        raise ValueError("assertion expired")
    if audience:
        auds = [a.text for a in cond.findall(".//saml:Audience", NS)]
        if audience not in auds:
            raise ValueError("audience restriction mismatch")


def _attributes(assertion: ET.Element) -> dict:
    out: dict = {}
    for attr in assertion.findall(".//saml:Attribute", NS):
        values = [v.text or "" for v in
                  attr.findall("saml:AttributeValue", NS)]
        out[attr.get("Name")] = values
    return out


def authenticate(scheme: str, response: str) -> dict:
    if scheme not in ("saml-entra-id", "saml-okta"):
        return {"authenticated": False, "errors": "invalid SSO scheme"}
    se = "ENTRA_ID" if scheme == "saml-entra-id" else "OKTA"
    env = os.environ.get
    try:
        xml = base64.b64decode(response)
        root = ET.fromstring(xml)
        assertion = root.find(".//saml:Assertion", NS)
        if assertion is None:
            raise ValueError("no assertion in response")
        cert_path = env(f"MEMGRAPH_SSO_{se}_SAML_IDP_CERT", "")
        if not cert_path:
            raise ValueError("IdP certificate not configured")
        verify_signature(root, assertion, _load_idp_cert(cert_path))
        idp_id = env(f"MEMGRAPH_SSO_{se}_SAML_IDP_ID", "")
        if idp_id:
            issuer = assertion.find("saml:Issuer", NS)
            if issuer is None or issuer.text != idp_id:
                raise ValueError("issuer mismatch")
        _check_conditions(
            assertion, env(f"MEMGRAPH_SSO_{se}_SAML_ASSERTION_AUDIENCE", ""))

        attrs = _attributes(assertion)
        role_attr = (ENTRA_ROLE_ATTR if scheme == "saml-entra-id"
                     else env("MEMGRAPH_SSO_OKTA_SAML_ROLE_ATTRIBUTE", ""))
        if role_attr not in attrs:
            raise ValueError("role attribute missing from assertion")
        idp_role = attrs[role_attr]
        idp_role = idp_role[0] if isinstance(idp_role, list) else idp_role

        mappings_raw = "".join(
            env(f"MEMGRAPH_SSO_{se}_SAML_ROLE_MAPPING", "").split(" "))
        mappings = dict(m.split(":") for m in mappings_raw.split(";") if m)
        if idp_role not in mappings:
            raise ValueError(
                f"the role {idp_role!r} is not present in the role mappings")

        use_name_id = env(f"MEMGRAPH_SSO_{se}_SAML_USE_NAME_ID",
                          "true").lower() in ("true", "1", "yes")
        if use_name_id:
            name_id = assertion.find(".//saml:NameID", NS)
            if name_id is None or not name_id.text:
                raise ValueError("NameID not found in assertion")
            username = name_id.text
        else:
            uattr = env(f"MEMGRAPH_SSO_{se}_SAML_USERNAME_ATTRIBUTE", "")
            if uattr not in attrs or not attrs[uattr]:
                raise ValueError(f"username attribute {uattr!r} missing")
            username = attrs[uattr][0]
        return {"authenticated": True, "username": username,
                "role": mappings[idp_role]}
    except Exception as e:  # noqa: BLE001 — the host treats errors as deny
        return {"authenticated": False, "errors": str(e)}


def main() -> None:
    for line in sys.stdin:
        if not line.strip():
            continue
        try:
            params = json.loads(line)
            ret = authenticate(params.get("scheme", ""),
                               params.get("response", ""))
        except Exception as e:  # noqa: BLE001
            ret = {"authenticated": False, "errors": str(e)}
        sys.stdout.write(json.dumps(ret) + "\n")
        sys.stdout.flush()


if __name__ == "__main__":
    main()
