#!/usr/bin/env python3
"""Reference auth module: users/roles from a JSON file.

Protocol (auth/module.py; reference: src/auth/reference_modules/): one
JSON line per request on stdin {"scheme", "username", "response"}, one
JSON line reply on stdout {"authenticated", "username", "role"}.

Config: AUTH_USERFILE env var -> {"users": {name: {"password": ...,
"role": ...}}}. Stands in for an IdP in tests and air-gapped deploys.
"""

import json
import os
import sys


def main():
    with open(os.environ["AUTH_USERFILE"]) as f:
        users = json.load(f)["users"]
    for line in sys.stdin:
        try:
            req = json.loads(line)
            user = users.get(req.get("username", ""))
            ok = user is not None and \
                user.get("password") == req.get("response")
            reply = {"authenticated": bool(ok)}
            if ok:
                reply["username"] = req["username"]
                reply["role"] = user.get("role", "")
        except Exception as e:  # noqa: BLE001 — reply, never crash
            reply = {"authenticated": False, "errors": str(e)}
        sys.stdout.write(json.dumps(reply) + "\n")
        sys.stdout.flush()


if __name__ == "__main__":
    main()
