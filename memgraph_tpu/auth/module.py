"""External auth modules: subprocess JSON line protocol.

Counterpart of the reference's auth module host
(/root/reference/src/auth/module.hpp:30 + reference_modules/): an
executable is spawned once and kept alive; each authentication request
writes ONE JSON line {"username", "password", ...} to its stdin and
reads ONE JSON line {"authenticated": bool, "role": str} back, under a
timeout. Any protocol violation (crash, timeout, malformed output,
missing fields) denies authentication — the module is trusted to say
yes, never assumed to.

Scheme routing: `module_mappings` ("saml:/path;oidc:/path") binds Bolt
auth schemes to executables, as the reference's
--auth-module-mappings flag does; the reserved name "basic" cannot be
remapped.
"""

from __future__ import annotations

import json
import logging
import os
import subprocess
import threading

log = logging.getLogger(__name__)

CALL_TIMEOUT_SEC = 10.0


class AuthModule:
    """One external module executable, restarted on failure."""

    def __init__(self, executable: str,
                 timeout: float = CALL_TIMEOUT_SEC) -> None:
        self.executable = executable
        self.timeout = timeout
        self._proc: subprocess.Popen | None = None
        self._lock = threading.Lock()

    def _ensure_proc(self) -> subprocess.Popen:
        if self._proc is None or self._proc.poll() is not None:
            self._proc = subprocess.Popen(
                [self.executable], stdin=subprocess.PIPE,
                stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                text=True, bufsize=1)
        return self._proc

    def call(self, params: dict) -> dict | None:
        """One request/response; None on ANY protocol failure."""
        with self._lock:
            try:
                proc = self._ensure_proc()
                proc.stdin.write(json.dumps(params) + "\n")
                proc.stdin.flush()
                line = _read_line_with_timeout(proc, self.timeout)
                if line is None:
                    self._kill()
                    return None
                reply = json.loads(line)
                if not isinstance(reply, dict):
                    return None
                return reply
            except (OSError, ValueError, json.JSONDecodeError) as e:
                log.warning("auth module %s failed: %s", self.executable, e)
                self._kill()
                return None

    def _kill(self) -> None:
        if self._proc is not None:
            try:
                self._proc.kill()
            except OSError:
                pass
            self._proc = None

    def close(self) -> None:
        with self._lock:
            self._kill()


def _read_line_with_timeout(proc: subprocess.Popen, timeout: float):
    """Read one stdout line; None on timeout (a wedged module must not
    hang the Bolt worker)."""
    result: list = [None]

    def reader():
        try:
            result[0] = proc.stdout.readline()
        except (OSError, ValueError):
            result[0] = None

    t = threading.Thread(target=reader, daemon=True)
    t.start()
    t.join(timeout)
    if t.is_alive() or not result[0]:
        return None
    return result[0]


def parse_module_mappings(spec: str) -> dict[str, AuthModule]:
    """'saml:/path/a.py;oidc:/path/b.py' -> {scheme: AuthModule}."""
    out: dict[str, AuthModule] = {}
    for part in filter(None, (spec or "").split(";")):
        scheme, _, path = part.partition(":")
        scheme = scheme.strip().lower()
        path = path.strip()
        if not scheme or not path or scheme == "basic":
            log.warning("ignoring invalid auth module mapping %r", part)
            continue
        if not os.access(path, os.X_OK):
            log.warning("auth module %r is not executable; ignoring", path)
            continue
        out[scheme] = AuthModule(path)
    return out
