"""Users, roles, permissions (AuthN/AuthZ).

Capability map to the reference's auth layer (/root/reference/src/auth/):
users with salted-hash passwords (PBKDF2 — the stdlib-available equivalent
of the reference's bcrypt, auth/crypto.cpp), roles, per-privilege
GRANT/DENY, durable via JSON (kvstore analog lands with durability dir).
"""

from __future__ import annotations

import hashlib
import json
import os
import secrets
import threading
from dataclasses import dataclass, field

from ..exceptions import AuthException

PRIVILEGES = [
    "CREATE", "DELETE", "MATCH", "MERGE", "SET", "REMOVE", "INDEX", "STATS",
    "CONSTRAINT", "DUMP", "REPLICATION", "DURABILITY", "READ_FILE",
    "FREE_MEMORY", "TRIGGER", "CONFIG", "AUTH", "STREAM", "MODULE_READ",
    "MODULE_WRITE", "WEBSOCKET", "TRANSACTION_MANAGEMENT", "STORAGE_MODE",
    "MULTI_DATABASE_EDIT", "MULTI_DATABASE_USE", "COORDINATOR",
]


def _hash_password(password: str, salt: bytes | None = None) -> str:
    if salt is None:
        salt = secrets.token_bytes(16)
    digest = hashlib.pbkdf2_hmac("sha256", password.encode("utf-8"), salt,
                                 100_000)
    return salt.hex() + "$" + digest.hex()


def _verify_password(password: str, stored: str) -> bool:
    try:
        salt_hex, digest_hex = stored.split("$", 1)
    except ValueError:
        return False
    digest = hashlib.pbkdf2_hmac("sha256", password.encode("utf-8"),
                                 bytes.fromhex(salt_hex), 100_000)
    return secrets.compare_digest(digest.hex(), digest_hex)


@dataclass
class Role:
    name: str
    granted: set = field(default_factory=set)
    denied: set = field(default_factory=set)


@dataclass
class User:
    name: str
    password_hash: str | None = None
    roles: list[str] = field(default_factory=list)
    granted: set = field(default_factory=set)
    denied: set = field(default_factory=set)


class Auth:
    def __init__(self, storage_path: str | None = None) -> None:
        self._lock = threading.Lock()
        self._users: dict[str, User] = {}
        self._roles: dict[str, Role] = {}
        self._path = storage_path
        if storage_path and os.path.exists(storage_path):
            self._load()

    # --- users --------------------------------------------------------------

    def create_user(self, name: str, password: str | None = None) -> None:
        with self._lock:
            if name in self._users:
                raise AuthException(f"user {name!r} already exists")
            user = User(name, _hash_password(password) if password else None)
            if not self._users:
                # the first user becomes the administrator (full grants) —
                # otherwise enabling auth would lock everyone out
                user.granted = set(PRIVILEGES)
            self._users[name] = user
            self._save()

    def drop_user(self, name: str) -> None:
        with self._lock:
            if name not in self._users:
                raise AuthException(f"user {name!r} does not exist")
            del self._users[name]
            self._save()

    def set_password(self, name: str, password: str | None) -> None:
        with self._lock:
            user = self._users.get(name)
            if user is None:
                raise AuthException(f"user {name!r} does not exist")
            user.password_hash = _hash_password(password) if password else None
            self._save()

    def authenticate(self, name: str, password: str) -> bool:
        with self._lock:
            if not self._users:
                return True  # no users defined → open instance (reference behavior)
            user = self._users.get(name)
            if user is None:
                return False
            if user.password_hash is None:
                return True
            return _verify_password(password, user.password_hash)

    def users(self) -> list[str]:
        with self._lock:
            return sorted(self._users)

    def user_roles(self, name: str) -> list[str]:
        with self._lock:
            user = self._users.get(name)
            return sorted(user.roles) if user is not None else []

    def roles(self) -> list[str]:
        with self._lock:
            return sorted(self._roles)

    def _resolve_locked(self, name: str, privilege: str) -> str | None:
        """Single resolution routine shared by enforcement and reporting:
        user deny > user grant > role deny > role grant. Returns 'GRANT',
        'DENY', or None (no opinion). Caller holds self._lock."""
        user = self._users.get(name)
        if user is not None:
            if privilege in user.denied:
                return "DENY"
            if privilege in user.granted:
                return "GRANT"
            role_granted = False
            for role_name in user.roles:
                role = self._roles.get(role_name)
                if role is None:
                    continue
                if privilege in role.denied:
                    return "DENY"
                if privilege in role.granted:
                    role_granted = True
            return "GRANT" if role_granted else None
        role = self._roles.get(name)
        if role is not None:
            if privilege in role.denied:
                return "DENY"
            if privilege in role.granted:
                return "GRANT"
        return None

    def effective_privileges(self, name: str) -> list[tuple[str, str]]:
        """[(privilege, 'GRANT'|'DENY')] for a user or role; raises for
        unknown names. Uses the same resolution order as has_privilege
        so SHOW PRIVILEGES never contradicts enforcement."""
        with self._lock:
            if name not in self._users and name not in self._roles:
                raise AuthException(f"user or role {name!r} does not exist")
            out = []
            for p in PRIVILEGES:
                verdict = self._resolve_locked(name, p)
                if verdict is not None:
                    out.append((p, verdict))
            return out

    # --- roles / privileges -------------------------------------------------

    def create_role(self, name: str) -> None:
        with self._lock:
            if name in self._roles:
                raise AuthException(f"role {name!r} already exists")
            self._roles[name] = Role(name)
            self._save()

    def drop_role(self, name: str) -> None:
        with self._lock:
            self._roles.pop(name, None)
            for user in self._users.values():
                if name in user.roles:
                    user.roles.remove(name)
            self._save()

    def set_role(self, user: str, role: str) -> None:
        with self._lock:
            if user not in self._users:
                raise AuthException(f"user {user!r} does not exist")
            if role not in self._roles:
                raise AuthException(f"role {role!r} does not exist")
            if role not in self._users[user].roles:
                self._users[user].roles.append(role)
            self._save()

    def grant(self, name: str, privileges: list[str]) -> None:
        self._change_privileges(name, privileges, "grant")

    def deny(self, name: str, privileges: list[str]) -> None:
        self._change_privileges(name, privileges, "deny")

    def revoke(self, name: str, privileges: list[str]) -> None:
        self._change_privileges(name, privileges, "revoke")

    def _change_privileges(self, name, privileges, action) -> None:
        privileges = [p.upper() for p in privileges]
        for p in privileges:
            if p != "ALL" and p not in PRIVILEGES:
                raise AuthException(f"unknown privilege {p}")
        with self._lock:
            target = self._users.get(name) or self._roles.get(name)
            if target is None:
                raise AuthException(f"user or role {name!r} does not exist")
            plist = PRIVILEGES if "ALL" in privileges else privileges
            for p in plist:
                if action == "grant":
                    target.granted.add(p)
                    target.denied.discard(p)
                elif action == "deny":
                    target.denied.add(p)
                    target.granted.discard(p)
                else:
                    target.granted.discard(p)
                    target.denied.discard(p)
            self._save()

    def has_privilege(self, user_name: str, privilege: str) -> bool:
        with self._lock:
            if not self._users:
                return True
            if user_name not in self._users:
                return False
            return self._resolve_locked(user_name, privilege) == "GRANT"

    # --- durability ---------------------------------------------------------

    def _save(self) -> None:
        if not self._path:
            return
        data = {
            "users": [{"name": u.name, "password_hash": u.password_hash,
                       "roles": u.roles, "granted": sorted(u.granted),
                       "denied": sorted(u.denied)}
                      for u in self._users.values()],
            "roles": [{"name": r.name, "granted": sorted(r.granted),
                       "denied": sorted(r.denied)}
                      for r in self._roles.values()],
        }
        tmp = self._path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(data, f)
        os.replace(tmp, self._path)

    def _load(self) -> None:
        with open(self._path) as f:
            data = json.load(f)
        for u in data.get("users", []):
            self._users[u["name"]] = User(
                u["name"], u.get("password_hash"), u.get("roles", []),
                set(u.get("granted", [])), set(u.get("denied", [])))
        for r in data.get("roles", []):
            self._roles[r["name"]] = Role(
                r["name"], set(r.get("granted", [])),
                set(r.get("denied", [])))


_GLOBAL_AUTH: Auth | None = None
_GLOBAL_LOCK = threading.Lock()


def resolve_auth(interpreter_context) -> Auth:
    """The Auth store a session should consult: the context's wired
    auth_store, else the process-global one. Single source for both RBAC
    enforcement (Interpreter._auth_store) and the roles() builtin."""
    auth = getattr(interpreter_context, "auth_store", None)
    return auth if auth is not None else global_auth()


def global_auth() -> Auth:
    global _GLOBAL_AUTH
    with _GLOBAL_LOCK:
        if _GLOBAL_AUTH is None:
            _GLOBAL_AUTH = Auth()
        return _GLOBAL_AUTH
