"""Users, roles, permissions (AuthN/AuthZ).

Capability map to the reference's auth layer (/root/reference/src/auth/):
users with salted-hash passwords (PBKDF2 — the stdlib-available equivalent
of the reference's bcrypt, auth/crypto.cpp), roles, per-privilege
GRANT/DENY, durable via JSON (kvstore analog lands with durability dir).
"""

from __future__ import annotations

import hashlib
import json
import os
import secrets
import threading
from dataclasses import dataclass, field

from ..exceptions import AuthException

PRIVILEGES = [
    "CREATE", "DELETE", "MATCH", "MERGE", "SET", "REMOVE", "INDEX", "STATS",
    "CONSTRAINT", "DUMP", "REPLICATION", "DURABILITY", "READ_FILE",
    "FREE_MEMORY", "TRIGGER", "CONFIG", "AUTH", "STREAM", "MODULE_READ",
    "MODULE_WRITE", "WEBSOCKET", "TRANSACTION_MANAGEMENT", "STORAGE_MODE",
    "MULTI_DATABASE_EDIT", "MULTI_DATABASE_USE", "COORDINATOR",
]


def _hash_password(password: str, salt: bytes | None = None) -> str:
    if salt is None:
        salt = secrets.token_bytes(16)
    digest = hashlib.pbkdf2_hmac("sha256", password.encode("utf-8"), salt,
                                 100_000)
    return salt.hex() + "$" + digest.hex()


def _verify_password(password: str, stored: str) -> bool:
    try:
        salt_hex, digest_hex = stored.split("$", 1)
    except ValueError:
        return False
    digest = hashlib.pbkdf2_hmac("sha256", password.encode("utf-8"),
                                 bytes.fromhex(salt_hex), 100_000)
    return secrets.compare_digest(digest.hex(), digest_hex)


@dataclass
class Role:
    name: str
    granted: set = field(default_factory=set)
    denied: set = field(default_factory=set)
    # fine-grained: label/edge-type name (or "*") -> access level
    fg_labels: dict = field(default_factory=dict)
    fg_edge_types: dict = field(default_factory=dict)


@dataclass
class User:
    name: str
    password_hash: str | None = None
    roles: list[str] = field(default_factory=list)
    granted: set = field(default_factory=set)
    denied: set = field(default_factory=set)
    fg_labels: dict = field(default_factory=dict)
    fg_edge_types: dict = field(default_factory=dict)
    # module-managed (SSO) identity: basic-scheme login is REFUSED for
    # these users (a passwordless external user must not be open)
    external: bool = False


class Auth:
    def __init__(self, storage_path: str | None = None,
                 module_mappings: dict | None = None) -> None:
        self._lock = threading.Lock()
        self._users: dict[str, User] = {}
        self._roles: dict[str, Role] = {}
        self._path = storage_path
        # scheme -> AuthModule (SSO/external auth; auth/module.py)
        self.module_mappings = dict(module_mappings or {})
        if storage_path and os.path.exists(storage_path):
            self._load()

    # --- external (SSO) authentication --------------------------------------

    def authenticate_external(self, scheme: str, principal: str,
                              credentials) -> str | None:
        """Route a non-basic Bolt auth scheme through its external
        module. Returns the authenticated username, or None.

        The module decides identity AND role
        ({"authenticated": true, "username": ..., "role": ...}); the
        user is auto-created on first login and its role assignment
        follows the module on every login (reference: SSO users are
        module-managed, auth/module.cpp)."""
        module = self.module_mappings.get((scheme or "").lower())
        if module is None:
            return None
        reply = module.call({"scheme": scheme, "username": principal,
                             "response": credentials})
        if not reply or reply.get("authenticated") is not True:
            return None
        username = reply.get("username") or principal
        if not isinstance(username, str) or not username:
            return None
        # modules may return a single "role" or a "roles" list (the OIDC
        # flow maps one IdP role to several local roles)
        roles = reply.get("roles")
        if not isinstance(roles, list):
            role = reply.get("role")
            roles = [role] if isinstance(role, str) and role else []
        roles = [r for r in roles if isinstance(r, str) and r]
        with self._lock:
            changed = False
            user = self._users.get(username)
            if user is None:
                user = User(username, None, external=True)
                self._users[username] = user
                changed = True
            if roles:
                for role in roles:
                    if role not in self._roles:
                        self._roles[role] = Role(role)
                        changed = True
                new_roles = list(dict.fromkeys(roles))
            else:
                # the module is authoritative on EVERY login: a reply
                # without a role revokes previous module-granted roles
                new_roles = []
            if user.roles != new_roles:
                user.roles = new_roles
                changed = True
            if changed:   # reconnect storms must not rewrite the store
                self._save()
        return username

    # --- users --------------------------------------------------------------

    def create_user(self, name: str, password: str | None = None) -> None:
        with self._lock:
            if name in self._users:
                raise AuthException(f"user {name!r} already exists")
            user = User(name, _hash_password(password) if password else None)
            if not self._users:
                # the first user becomes the administrator (full grants) —
                # otherwise enabling auth would lock everyone out
                user.granted = set(PRIVILEGES)
            self._users[name] = user
            self._save()

    def drop_user(self, name: str) -> None:
        with self._lock:
            if name not in self._users:
                raise AuthException(f"user {name!r} does not exist")
            del self._users[name]
            self._save()

    def set_password(self, name: str, password: str | None) -> None:
        with self._lock:
            user = self._users.get(name)
            if user is None:
                raise AuthException(f"user {name!r} does not exist")
            user.password_hash = _hash_password(password) if password else None
            self._save()

    def authenticate(self, name: str, password: str) -> bool:
        with self._lock:
            if not self._users:
                return True  # no users defined → open instance (reference behavior)
            user = self._users.get(name)
            if user is None:
                return False
            if user.external:
                # SSO identities authenticate ONLY through their module
                return False
            if user.password_hash is None:
                return True
            return _verify_password(password, user.password_hash)

    def users(self) -> list[str]:
        with self._lock:
            return sorted(self._users)

    def user_roles(self, name: str) -> list[str]:
        with self._lock:
            user = self._users.get(name)
            return sorted(user.roles) if user is not None else []

    def roles(self) -> list[str]:
        with self._lock:
            return sorted(self._roles)

    def _resolve_locked(self, name: str, privilege: str) -> str | None:
        """Single resolution routine shared by enforcement and reporting:
        user deny > user grant > role deny > role grant. Returns 'GRANT',
        'DENY', or None (no opinion). Caller holds self._lock."""
        user = self._users.get(name)
        if user is not None:
            if privilege in user.denied:
                return "DENY"
            if privilege in user.granted:
                return "GRANT"
            role_granted = False
            for role_name in user.roles:
                role = self._roles.get(role_name)
                if role is None:
                    continue
                if privilege in role.denied:
                    return "DENY"
                if privilege in role.granted:
                    role_granted = True
            return "GRANT" if role_granted else None
        role = self._roles.get(name)
        if role is not None:
            if privilege in role.denied:
                return "DENY"
            if privilege in role.granted:
                return "GRANT"
        return None

    def effective_privileges(self, name: str) -> list[tuple[str, str]]:
        """[(privilege, 'GRANT'|'DENY')] for a user or role; raises for
        unknown names. Uses the same resolution order as has_privilege
        so SHOW PRIVILEGES never contradicts enforcement."""
        with self._lock:
            if name not in self._users and name not in self._roles:
                raise AuthException(f"user or role {name!r} does not exist")
            out = []
            for p in PRIVILEGES:
                verdict = self._resolve_locked(name, p)
                if verdict is not None:
                    out.append((p, verdict))
            return out

    # --- roles / privileges -------------------------------------------------

    def create_role(self, name: str) -> None:
        with self._lock:
            if name in self._roles:
                raise AuthException(f"role {name!r} already exists")
            self._roles[name] = Role(name)
            self._save()

    def drop_role(self, name: str) -> None:
        with self._lock:
            self._roles.pop(name, None)
            for user in self._users.values():
                if name in user.roles:
                    user.roles.remove(name)
            self._save()

    def set_role(self, user: str, role: str) -> None:
        with self._lock:
            if user not in self._users:
                raise AuthException(f"user {user!r} does not exist")
            if role not in self._roles:
                raise AuthException(f"role {role!r} does not exist")
            if role not in self._users[user].roles:
                self._users[user].roles.append(role)
            self._save()

    def grant(self, name: str, privileges: list[str]) -> None:
        self._change_privileges(name, privileges, "grant")

    def deny(self, name: str, privileges: list[str]) -> None:
        self._change_privileges(name, privileges, "deny")

    def revoke(self, name: str, privileges: list[str]) -> None:
        self._change_privileges(name, privileges, "revoke")

    def _change_privileges(self, name, privileges, action) -> None:
        privileges = [p.upper() for p in privileges]
        for p in privileges:
            if p != "ALL" and p not in PRIVILEGES:
                raise AuthException(f"unknown privilege {p}")
        with self._lock:
            target = self._users.get(name) or self._roles.get(name)
            if target is None:
                raise AuthException(f"user or role {name!r} does not exist")
            plist = PRIVILEGES if "ALL" in privileges else privileges
            for p in plist:
                if action == "grant":
                    target.granted.add(p)
                    target.denied.discard(p)
                elif action == "deny":
                    target.denied.add(p)
                    target.granted.discard(p)
                else:
                    target.granted.discard(p)
                    target.denied.discard(p)
            self._save()

    def grant_fine_grained(self, name: str, kind: str, items: list[str],
                           level: str) -> None:
        """kind: 'labels' | 'edge_types'; items may be ['*']."""
        if level not in FG_LEVELS:
            raise AuthException(f"unknown access level {level!r}")
        with self._lock:
            p = self._users.get(name) or self._roles.get(name)
            if p is None:
                raise AuthException(f"no such user or role {name!r}")
            target = p.fg_labels if kind == "labels" else p.fg_edge_types
            for item in items:
                target[item] = level
            self._save()

    def revoke_fine_grained(self, name: str, kind: str,
                            items: list[str]) -> None:
        with self._lock:
            p = self._users.get(name) or self._roles.get(name)
            if p is None:
                raise AuthException(f"no such user or role {name!r}")
            target = p.fg_labels if kind == "labels" else p.fg_edge_types
            for item in items:
                target.pop(item, None)
            self._save()

    def fine_grained_checker(self, username: str,
                             allow_role: bool = False
                             ) -> "FineGrainedChecker":
        """allow_role=True additionally resolves a bare role name (for
        SHOW PRIVILEGES inspection); the runtime authorization path must
        keep it False so a dropped user never inherits a same-named
        role's rules."""
        return FineGrainedChecker(self, username, allow_role=allow_role)

    def has_privilege(self, user_name: str, privilege: str) -> bool:
        with self._lock:
            if not self._users:
                return True
            if user_name not in self._users:
                return False
            return self._resolve_locked(user_name, privilege) == "GRANT"

    # --- durability ---------------------------------------------------------

    def to_dict(self) -> dict:
        """Full-state dump for system replication (reference analog: the
        ordered auth system txns of src/system/transaction.cpp; the store
        is small, so full-state transfer is idempotent and order-safe)."""
        with self._lock:
            return self._dump_locked()

    def apply_dict(self, data: dict) -> None:
        """Replace contents with a to_dict() dump (replica apply)."""
        with self._lock:
            self._users.clear()
            self._roles.clear()
            self._load_data(data)
            self._save()

    def _dump_locked(self) -> dict:
        return {
            "users": [{"name": u.name, "password_hash": u.password_hash,
                       "roles": u.roles, "granted": sorted(u.granted),
                       "denied": sorted(u.denied),
                       "fg_labels": u.fg_labels,
                       "fg_edge_types": u.fg_edge_types,
                       "external": u.external}
                      for u in self._users.values()],
            "roles": [{"name": r.name, "granted": sorted(r.granted),
                       "denied": sorted(r.denied),
                       "fg_labels": r.fg_labels,
                       "fg_edge_types": r.fg_edge_types}
                      for r in self._roles.values()],
        }

    def _load_data(self, data: dict) -> None:
        for u in data.get("users", []):
            self._users[u["name"]] = User(
                u["name"], u.get("password_hash"), u.get("roles", []),
                set(u.get("granted", [])), set(u.get("denied", [])),
                dict(u.get("fg_labels", {})),
                dict(u.get("fg_edge_types", {})),
                external=bool(u.get("external", False)))
        for r in data.get("roles", []):
            self._roles[r["name"]] = Role(
                r["name"], set(r.get("granted", [])),
                set(r.get("denied", [])),
                dict(r.get("fg_labels", {})),
                dict(r.get("fg_edge_types", {})))

    def _save(self) -> None:
        if not self._path:
            return
        data = {
            "users": [{"name": u.name, "password_hash": u.password_hash,
                       "roles": u.roles, "granted": sorted(u.granted),
                       "denied": sorted(u.denied),
                       "fg_labels": u.fg_labels,
                       "fg_edge_types": u.fg_edge_types,
                       "external": u.external}
                      for u in self._users.values()],
            "roles": [{"name": r.name, "granted": sorted(r.granted),
                       "denied": sorted(r.denied),
                       "fg_labels": r.fg_labels,
                       "fg_edge_types": r.fg_edge_types}
                      for r in self._roles.values()],
        }
        tmp = self._path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(data, f)
        os.replace(tmp, self._path)

    def _load(self) -> None:
        with open(self._path) as f:
            data = json.load(f)
        self._load_data(data)


# --- fine-grained (label-based) access -------------------------------------
# Reference: src/auth/models.cpp FineGrainedAccessPermissions — per-label /
# per-edge-type levels NOTHING < READ < UPDATE < CREATE_DELETE, with "*"
# as the global fallback rule.

FG_LEVELS = {"NOTHING": 0, "READ": 1, "UPDATE": 2, "CREATE_DELETE": 3}


class FineGrainedChecker:
    """Resolved per-session view of a user's label/edge-type permissions.

    Resolution per item: user-specific rule > user "*" > role-specific >
    role "*". A principal with NO fine-grained rules anywhere is
    unrestricted (fine-grained is opt-in, as in the reference); once any
    rule exists, unmatched items default to NOTHING.
    """

    def __init__(self, auth: "Auth", username: str,
                 allow_role: bool = False) -> None:
        # kept as SEPARATE chains: a user's "*" rule must shadow a role's
        # label-specific rule, which a flat merge cannot express
        self._label_chain: list[dict] = []
        self._etype_chain: list[dict] = []
        with auth._lock:
            user = auth._users.get(username)
            if user is None and allow_role and username in auth._roles:
                # allow inspecting a ROLE's fine-grained rules directly
                role = auth._roles[username]
                self._label_chain.append(
                    {k: FG_LEVELS.get(v, 0)
                     for k, v in role.fg_labels.items()})
                self._etype_chain.append(
                    {k: FG_LEVELS.get(v, 0)
                     for k, v in role.fg_edge_types.items()})
            if user is not None:
                self._label_chain.append(
                    {k: FG_LEVELS.get(v, 0) for k, v in user.fg_labels.items()})
                self._etype_chain.append(
                    {k: FG_LEVELS.get(v, 0)
                     for k, v in user.fg_edge_types.items()})
                for rn in user.roles:
                    role = auth._roles.get(rn)
                    if role is not None:
                        self._label_chain.append(
                            {k: FG_LEVELS.get(v, 0)
                             for k, v in role.fg_labels.items()})
                        self._etype_chain.append(
                            {k: FG_LEVELS.get(v, 0)
                             for k, v in role.fg_edge_types.items()})
        self.restricted = any(self._label_chain) or any(self._etype_chain)
        # flattened views for SHOW PRIVILEGES (resolution order preserved)
        self._labels: dict[str, int] = {}
        self._edge_types: dict[str, int] = {}
        for keys, chain, out in (("l", self._label_chain, self._labels),
                                 ("e", self._etype_chain, self._edge_types)):
            for rules in chain:
                for k in rules:
                    out.setdefault(
                        k, self._resolve(chain, k))

    @staticmethod
    def _resolve(chain: list[dict], name: str) -> int:
        """First chain entry (user, then roles in order) that has either a
        specific rule or a "*" rule decides."""
        for rules in chain:
            if name in rules:
                return rules[name]
            if "*" in rules:
                return rules["*"]
        return 0

    def label_level(self, name: str) -> int:
        if not self.restricted:
            return 3
        return self._resolve(self._label_chain, name)

    def edge_type_level(self, name: str) -> int:
        if not self.restricted:
            return 3
        return self._resolve(self._etype_chain, name)

    # vertex rules: the level of a vertex is the MINIMUM over its labels
    # (an unlabeled vertex is unrestricted), matching the reference's
    # FineGrainedAuthChecker vertex accumulation
    def vertex_level(self, label_names) -> int:
        level = 3
        for name in label_names:
            level = min(level, self.label_level(name))
        return level


_GLOBAL_AUTH: Auth | None = None
_GLOBAL_LOCK = threading.Lock()


def resolve_auth(interpreter_context) -> Auth:
    """The Auth store a session should consult: the context's wired
    auth_store, else the process-global one. Single source for both RBAC
    enforcement (Interpreter._auth_store) and the roles() builtin."""
    auth = getattr(interpreter_context, "auth_store", None)
    return auth if auth is not None else global_auth()


def global_auth() -> Auth:
    global _GLOBAL_AUTH
    with _GLOBAL_LOCK:
        if _GLOBAL_AUTH is None:
            _GLOBAL_AUTH = Auth()
        return _GLOBAL_AUTH
