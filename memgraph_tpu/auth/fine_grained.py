"""Storage-level fine-grained (label-based) access filtering.

The id-space adapter between the name-keyed FineGrainedChecker
(auth.auth.FineGrainedChecker, reference src/auth/models.cpp) and the
storage accessors, which deal in interned label/edge-type ids. Levels:
NOTHING(0) < READ(1) < UPDATE(2) < CREATE_DELETE(3).

Attached to an Accessor as `accessor.fine_grained`; the accessor consults
it on every read (scan, expansion) and write (label/property mutation,
create/delete) — the single choke point both engines (in-memory and disk)
share, the same role the reference's FineGrainedAuthChecker plays inside
its operators.
"""

from __future__ import annotations

from ..exceptions import AuthException

READ, UPDATE, CREATE_DELETE = 1, 2, 3


class FgStorageView:
    def __init__(self, checker, storage) -> None:
        self._checker = checker
        self._label_mapper = storage.label_mapper
        self._edge_type_mapper = storage.edge_type_mapper
        self._label_cache: dict[int, int] = {}
        self._etype_cache: dict[int, int] = {}

    def label_level(self, label_id: int) -> int:
        lv = self._label_cache.get(label_id)
        if lv is None:
            lv = self._checker.label_level(
                self._label_mapper.id_to_name(label_id))
            self._label_cache[label_id] = lv
        return lv

    def edge_type_level(self, edge_type_id: int) -> int:
        lv = self._etype_cache.get(edge_type_id)
        if lv is None:
            lv = self._checker.edge_type_level(
                self._edge_type_mapper.id_to_name(edge_type_id))
            self._etype_cache[edge_type_id] = lv
        return lv

    def vertex_level(self, label_ids) -> int:
        level = 3
        for lid in label_ids:
            level = min(level, self.label_level(lid))
        return level

    # --- read filters -------------------------------------------------

    def can_read_vertex(self, label_ids) -> bool:
        return self.vertex_level(label_ids) >= READ

    def can_read_edge(self, edge_type_id: int) -> bool:
        return self.edge_type_level(edge_type_id) >= READ

    # --- write gates (raise on violation) -----------------------------

    def check_label_modify(self, label_id: int) -> None:
        if self.label_level(label_id) < CREATE_DELETE:
            raise AuthException(
                "not allowed to create/delete label "
                f":{self._label_mapper.id_to_name(label_id)}")

    def check_vertex_update(self, label_ids) -> None:
        if self.vertex_level(label_ids) < UPDATE:
            raise AuthException(
                "not allowed to update vertices with these labels")

    def check_vertex_delete(self, label_ids) -> None:
        if self.vertex_level(label_ids) < CREATE_DELETE:
            raise AuthException(
                "not allowed to delete vertices with these labels")

    def check_edge_create_delete(self, edge_type_id: int) -> None:
        if self.edge_type_level(edge_type_id) < CREATE_DELETE:
            raise AuthException(
                "not allowed to create/delete edges of type "
                f":{self._edge_type_mapper.id_to_name(edge_type_id)}")

    def check_edge_update(self, edge_type_id: int) -> None:
        if self.edge_type_level(edge_type_id) < UPDATE:
            raise AuthException(
                "not allowed to update edges of type "
                f":{self._edge_type_mapper.id_to_name(edge_type_id)}")
