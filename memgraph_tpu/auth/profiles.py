"""Per-user resource profiles.

Counterpart of the reference's UserProfiles
(/root/reference/src/auth/profiles/user_profiles.cpp + the
MemgraphCypher.g4:974-991 grammar): named profiles carrying the limits
`sessions` (max concurrent Bolt sessions) and `transactions_memory`
(per-query memory cap), assignable to users, persisted in the kvstore.

Enforcement here:
  - sessions: BoltSession registration counts live sessions per
    username and refuses logins over the limit.
  - transactions_memory: becomes the default per-query memory cap for
    that user (explicit QUERY MEMORY LIMIT still wins; combined with a
    tenant-profile cap the smaller one applies).
"""

from __future__ import annotations

import json
import threading

from ..exceptions import QueryException

_KEY = "user_profiles"
LIMIT_KEYS = ("sessions", "transactions_memory")


class UserProfiles:
    def __init__(self, kvstore=None) -> None:
        self._lock = threading.Lock()
        self._profiles: dict[str, dict] = {}
        self._assignments: dict[str, str] = {}   # username -> profile
        self._kv = kvstore
        if kvstore is not None:
            raw = kvstore.get_str(_KEY)
            if raw:
                data = json.loads(raw)
                self._profiles = data.get("profiles", {})
                self._assignments = data.get("assignments", {})

    def _save(self) -> None:
        if self._kv is not None:
            self._kv.put(_KEY, json.dumps(
                {"profiles": self._profiles,
                 "assignments": self._assignments}))

    @staticmethod
    def _check_limits(limits: dict) -> dict:
        for key in limits:
            if key not in LIMIT_KEYS:
                raise QueryException(
                    f"unknown profile limit {key!r}; supported: "
                    f"{', '.join(LIMIT_KEYS)}")
        return dict(limits)

    # --- DDL -----------------------------------------------------------------

    def create(self, name: str, limits: dict) -> None:
        with self._lock:
            if name in self._profiles:
                raise QueryException(f"profile {name!r} already exists")
            self._profiles[name] = self._check_limits(limits)
            self._save()

    def update(self, name: str, limits: dict) -> None:
        with self._lock:
            if name not in self._profiles:
                raise QueryException(f"profile {name!r} does not exist")
            self._profiles[name].update(self._check_limits(limits))
            self._save()

    def drop(self, name: str) -> None:
        with self._lock:
            if name not in self._profiles:
                raise QueryException(f"profile {name!r} does not exist")
            del self._profiles[name]
            self._assignments = {u: p for u, p in
                                 self._assignments.items() if p != name}
            self._save()

    def assign(self, username: str, profile: str) -> None:
        with self._lock:
            if profile not in self._profiles:
                raise QueryException(
                    f"profile {profile!r} does not exist")
            self._assignments[username] = profile
            self._save()

    def clear(self, username: str) -> None:
        with self._lock:
            self._assignments.pop(username, None)
            self._save()

    # --- reads ---------------------------------------------------------------

    def show(self, name: str | None = None) -> list[list]:
        with self._lock:
            items = (sorted(self._profiles.items()) if name is None
                     else [(name, self._profiles.get(name))])
            out = []
            for pname, limits in items:
                if limits is None:
                    raise QueryException(
                        f"profile {pname!r} does not exist")
                shown = {k: ("UNLIMITED" if limits.get(k) is None
                             else limits[k]) for k in LIMIT_KEYS
                         if k in limits}
                out.append([pname, shown])
            return out

    def profile_for(self, username: str):
        with self._lock:
            return self._assignments.get(username)

    def users_for(self, profile: str) -> list[str]:
        with self._lock:
            if profile not in self._profiles:
                raise QueryException(
                    f"profile {profile!r} does not exist")
            return sorted(u for u, p in self._assignments.items()
                          if p == profile)

    def limit_for_user(self, username: str, key: str):
        with self._lock:
            profile = self._assignments.get(username)
            if profile is None:
                return None
            return self._profiles.get(profile, {}).get(key)


def ensure_user_profiles(ictx) -> "UserProfiles":
    profiles = getattr(ictx, "user_profiles", None)
    if profiles is None:
        profiles = ictx.user_profiles = UserProfiles(
            getattr(ictx, "kvstore", None))
    return profiles
