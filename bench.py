"""North-star benchmark: PageRank edges/sec on a 10M-edge graph (BASELINE.md).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
  value       = TPU PageRank throughput in edges/sec (n_edges * iterations /
                wall seconds, compile excluded, fixed iteration count)
  vs_baseline = speedup over the CPU baseline: scipy.sparse CSR power
                iteration on this host — the same sparse-matvec formulation
                the reference's C++ pagerank module implements
                (/root/reference/mage/cpp/pagerank_module), measured on the
                same graph with the same iteration count.

Also verifies top-100 rank parity between the TPU and CPU implementations
(the BASELINE.json acceptance criterion) and reports CALL-to-first-record
latency through the module/CSR-cache path on a smaller stored graph.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

N_NODES = 1_000_000
N_EDGES = 10_000_000
ITERATIONS = 50
DAMPING = 0.85


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def generate_graph(n_nodes=N_NODES, n_edges=N_EDGES, seed=7):
    """Skewed random digraph: power-law-ish in-degree via squared sampling
    (supernode skew stresses the segment reductions, SURVEY.md §7)."""
    rng = np.random.default_rng(seed)
    src = rng.integers(0, n_nodes, n_edges, dtype=np.int64)
    # bias destinations toward low ids → heavy-tail in-degree
    dst = (rng.random(n_edges) ** 2 * n_nodes).astype(np.int64)
    return src, dst


def cpu_pagerank(src, dst, n_nodes, iterations=ITERATIONS, damping=DAMPING):
    """Baseline: scipy CSR power iteration (the C++ module's formulation)."""
    import scipy.sparse as sp
    w = np.ones(len(src), dtype=np.float64)
    deg = np.bincount(src, minlength=n_nodes).astype(np.float64)
    inv_deg = np.where(deg > 0, 1.0 / np.maximum(deg, 1), 0.0)
    # column-normalized adjacency: rank flows src -> dst
    mat = sp.csr_matrix((w * inv_deg[src], (dst, src)),
                        shape=(n_nodes, n_nodes))
    dangling = deg == 0
    rank = np.full(n_nodes, 1.0 / n_nodes)
    t0 = time.perf_counter()
    for _ in range(iterations):
        dm = rank[dangling].sum()
        rank = (1 - damping) / n_nodes + damping * (mat @ rank + dm / n_nodes)
    elapsed = time.perf_counter() - t0
    return rank, elapsed


def tpu_pagerank(graph, iterations=ITERATIONS, damping=DAMPING):
    from memgraph_tpu.ops.pagerank import _pagerank_kernel
    import jax.numpy as jnp

    def run(d):
        # CSC ((dst, src)-sorted) arrays — the kernel's required order
        return _pagerank_kernel(graph.csc_src, graph.csc_dst,
                                graph.csc_weights,
                                graph.src_idx, graph.weights,
                                jnp.int32(graph.n_nodes), graph.n_pad,
                                jnp.float32(d), iterations,
                                jnp.float32(0.0))  # tol=0 → fixed iterations

    # compile + warm up (excluded from timing); host-transfer forces
    # completion — block_until_ready is unreliable on the tunneled platform
    rank, err, iters = run(damping)
    _ = float(rank[0])
    t0 = time.perf_counter()
    rank, err, iters = run(damping)
    _ = float(rank[0])  # host sync
    elapsed = time.perf_counter() - t0
    assert int(iters) == iterations, f"expected {iterations}, ran {int(iters)}"
    return np.asarray(rank[:graph.n_nodes]), elapsed


def call_to_first_record_latency():
    """End-to-end module-path latency on a 100k-edge stored graph."""
    from memgraph_tpu.storage import InMemoryStorage, StorageConfig, StorageMode
    from memgraph_tpu.ops.csr import GraphCache
    from memgraph_tpu.ops.pagerank import pagerank

    storage = InMemoryStorage(StorageConfig(
        storage_mode=StorageMode.IN_MEMORY_ANALYTICAL))
    rng = np.random.default_rng(3)
    n, e = 20_000, 100_000
    acc = storage.access()
    et = storage.edge_type_mapper.name_to_id("E")
    vs = [acc.create_vertex() for _ in range(n)]
    for s, d in zip(rng.integers(0, n, e), rng.integers(0, n, e)):
        acc.create_edge(vs[s], vs[d], et)
    acc.commit()

    cache = GraphCache()
    acc = storage.access()
    t0 = time.perf_counter()
    g = cache.get(acc)
    ranks, _, _ = pagerank(g, max_iterations=100, tol=1e-6)
    first = (int(g.node_gids[0]), float(ranks[0]))
    cold = time.perf_counter() - t0
    t0 = time.perf_counter()
    g = cache.get(acc)
    ranks, _, _ = pagerank(g, max_iterations=100, tol=1e-6)
    ranks[0].block_until_ready()
    warm = time.perf_counter() - t0
    acc.abort()
    return cold, warm


def _arm_watchdog(seconds: int = 540):
    """Print a failure JSON line and exit if the bench wedges (e.g. the TPU
    tunnel is down) — the driver must always get its one line."""
    import signal

    def on_alarm(signum, frame):
        print(json.dumps({
            "metric": "pagerank_edges_per_sec_10M", "value": 0.0,
            "unit": "edges/s", "vs_baseline": 0.0,
            "extra": {"error": f"bench timed out after {seconds}s "
                               f"(device unreachable?)"}}))
        sys.stdout.flush()
        import os
        os._exit(0)

    signal.signal(signal.SIGALRM, on_alarm)
    signal.alarm(seconds)


def main():
    _arm_watchdog()
    import jax
    log(f"devices: {jax.devices()}")

    from memgraph_tpu.ops import csr

    log(f"generating {N_EDGES:,}-edge graph ...")
    src, dst = generate_graph()

    log("building CSR ...")
    t0 = time.perf_counter()
    graph = csr.from_coo(src, dst, n_nodes=N_NODES).to_device()
    log(f"  export+transfer: {time.perf_counter() - t0:.2f}s "
        f"(n_pad={graph.n_pad:,}, e_pad={graph.e_pad:,})")

    log("TPU pagerank ...")
    tpu_ranks, tpu_time = tpu_pagerank(graph)
    tpu_eps = N_EDGES * ITERATIONS / tpu_time
    log(f"  {tpu_time:.3f}s for {ITERATIONS} iterations -> {tpu_eps:,.0f} edges/s")

    log("CPU baseline (scipy CSR power iteration) ...")
    cpu_ranks, cpu_time = cpu_pagerank(src, dst, N_NODES)
    cpu_eps = N_EDGES * ITERATIONS / cpu_time
    log(f"  {cpu_time:.3f}s -> {cpu_eps:,.0f} edges/s")

    # acceptance: top-100 rank parity
    top_tpu = set(np.argsort(-tpu_ranks)[:100].tolist())
    top_cpu = set(np.argsort(-cpu_ranks)[:100].tolist())
    overlap = len(top_tpu & top_cpu)
    log(f"top-100 overlap: {overlap}/100")

    cold, warm = call_to_first_record_latency()
    log(f"CALL-to-first-record: cold={cold * 1e3:.1f}ms warm={warm * 1e3:.1f}ms")

    result = {
        "metric": "pagerank_edges_per_sec_10M",
        "value": round(tpu_eps, 1),
        "unit": "edges/s",
        "vs_baseline": round(tpu_eps / cpu_eps, 3),
        "extra": {
            "tpu_seconds_50iter": round(tpu_time, 4),
            "cpu_seconds_50iter": round(cpu_time, 4),
            "top100_overlap": overlap,
            "call_to_first_record_cold_ms": round(cold * 1e3, 1),
            "call_to_first_record_warm_ms": round(warm * 1e3, 1),
        },
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
