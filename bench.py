"""North-star benchmark: PageRank edges/sec on a 10M-edge graph (BASELINE.md).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
  value       = TPU PageRank throughput in edges/sec (n_edges * iterations /
                wall seconds, compile excluded, fixed iteration count)
  vs_baseline = speedup over the CPU baseline: scipy.sparse CSR power
                iteration on this host — the same sparse-matvec formulation
                the reference's C++ pagerank module implements
                (/root/reference/mage/cpp/pagerank_module), measured on the
                same graph with the same iteration count.

Hardening (round 2, after BENCH_r01 recorded 0.0 on a dead device tunnel):
  - the device is probed in a SUBPROCESS with a short timeout before the
    main process ever imports jax, so a wedged axon tunnel cannot hang us;
  - every device stage runs in a subprocess with its own timeout and a
    fallback ladder (axon @ 10M edges -> axon @ 1M -> jax-CPU @ 10M), so
    the driver always receives a nonzero measurement with the execution
    path recorded in "extra";
  - the scipy baseline runs first (pure numpy/scipy — cannot wedge).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time

import numpy as np

N_NODES = int(os.environ.get("BENCH_N_NODES", 1_000_000))
N_EDGES = int(os.environ.get("BENCH_N_EDGES", 10_000_000))
ITERATIONS = 50
DAMPING = 0.85

PROBE_TIMEOUT_SEC = 30
STAGE_TIMEOUT_SEC = 300
MASTER_TIMEOUT_SEC = int(os.environ.get("BENCH_MASTER_TIMEOUT", 530))

# best-so-far partial result; the belt-and-braces watchdog prints this, so
# a wedge after the CPU baseline still yields a nonzero, honest record.
# "degraded" starts True and is only cleared when the headline number came
# from the real accelerator at full size — a CPU fallback (BENCH_r05's 0.64×)
# can never again masquerade as the headline metric.
PARTIAL = {
    "metric": "pagerank_edges_per_sec_10M", "value": 0.0, "unit": "edges/s",
    "vs_baseline": 0.0, "degraded": True, "backend": "none",
    "extra": {"error": "bench wedged before any stage"},
}


def log(msg):
    print(msg, file=sys.stderr, flush=True)



def best_timed(once, budget_s=45.0, runs=3):
    """min-of-N wall time, adaptively: stop repeating once the cumulative
    timed spend exceeds budget_s, so a slow environment (fallback rungs,
    loaded host) never triples a stage that barely fit its timeout."""
    best, spent, result = float("inf"), 0.0, None
    for _ in range(runs):
        t0 = time.perf_counter()
        out = once()
        dt = time.perf_counter() - t0
        if dt < best:
            # keep result and time from the SAME run — device reductions
            # are not bit-deterministic across runs
            best, result = dt, out
        spent += dt
        if spent > budget_s:
            break
    return result, best


def generate_graph(n_nodes=N_NODES, n_edges=N_EDGES, seed=7):
    """Skewed random digraph: power-law-ish in-degree via squared sampling
    (supernode skew stresses the segment reductions, SURVEY.md §7)."""
    rng = np.random.default_rng(seed)
    src = rng.integers(0, n_nodes, n_edges, dtype=np.int64)
    # bias destinations toward low ids → heavy-tail in-degree
    dst = (rng.random(n_edges) ** 2 * n_nodes).astype(np.int64)
    return src, dst


def cpu_pagerank(src, dst, n_nodes, iterations=ITERATIONS, damping=DAMPING):
    """Baseline: scipy CSR power iteration (the C++ module's formulation)."""
    import scipy.sparse as sp
    w = np.ones(len(src), dtype=np.float64)
    deg = np.bincount(src, minlength=n_nodes).astype(np.float64)
    inv_deg = np.where(deg > 0, 1.0 / np.maximum(deg, 1), 0.0)
    # column-normalized adjacency: rank flows src -> dst
    mat = sp.csr_matrix((w * inv_deg[src], (dst, src)),
                        shape=(n_nodes, n_nodes))
    dangling = deg == 0
    # best-of-3: single-run wall time swings +-30% on this shared host,
    # which would swing vs_baseline by the same amount for free

    def once():
        rank = np.full(n_nodes, 1.0 / n_nodes)
        for _ in range(iterations):
            dm = rank[dangling].sum()
            rank = (1 - damping) / n_nodes \
                + damping * (mat @ rank + dm / n_nodes)
        return rank
    rank, elapsed = best_timed(once)
    return rank, elapsed


# --------------------------------------------------------------------------
# device-side stages (run in subprocesses; see --stage flags at the bottom)
# --------------------------------------------------------------------------

def stage_probe():
    """Tiny end-to-end device check through the SHARED probe path
    (kernel_server.probe_device — the same compiled-matmul+transfer
    check the resident daemon's health plane runs, fault-injectable via
    the device.* points). Exits 0 iff the device works."""
    import jax
    from memgraph_tpu.server.kernel_server import probe_device
    s, platform = probe_device()
    print(json.dumps({"devices": [str(d) for d in jax.devices()],
                      "platform": platform, "sum": s}))


def _classify_probe(rc) -> str:
    """Typed outcome for one subprocess probe attempt."""
    if rc == 0:
        return "ok"
    if rc is None:
        return "probe_timeout"
    if rc == 137:
        return "probe_killed"
    return f"probe_error_rc_{rc}"


def _resident_probe(timeout=20.0):
    """Consult the RESIDENT kernel server: its health reply plus its
    typed `probe` op. Returns (health_dict | None, probe_reply | None);
    never spawns a daemon — a probe consult must stay cheap."""
    try:
        from memgraph_tpu.server.kernel_server import (DEFAULT_SOCKET,
                                                       KernelClient)
    except Exception as e:  # noqa: BLE001 — environmental import failure
        log(f"  kernel-server import failed during probe consult: {e}")
        return None, None
    try:
        c = KernelClient(DEFAULT_SOCKET, timeout=timeout)
    except OSError:
        return None, None                # no resident daemon
    try:
        health = c.health()
    except Exception as e:  # noqa: BLE001 — daemon present but sick
        log(f"  resident kernel server health call failed: {e}")
        try:
            c.close()
        except OSError:
            pass
        return None, None
    probe_reply = None
    if not health.get("wedged"):
        try:
            probe_reply = c.probe()
        except Exception as e:  # noqa: BLE001 — typed reply preferred
            log(f"  resident kernel server probe failed: {e}")
    try:
        c.close()
    except OSError:
        pass
    return health, probe_reply


def stage_pagerank_mxu(n_nodes, n_edges, seed, out_path):
    """Gather-free MXU kernel (ops/spmv_mxu.py): plan from cache or fresh,
    run 50 fixed iterations on the device."""
    from memgraph_tpu.ops import spmv_mxu
    from memgraph_tpu.utils.jax_cache import ensure_compile_cache
    import jax
    import jax.numpy as jnp

    ensure_compile_cache()
    src, dst = generate_graph(n_nodes, n_edges, seed)
    cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             ".bench_cache")
    os.makedirs(cache_dir, exist_ok=True)
    cache = os.path.join(cache_dir,
                         f"mxu_plan_{n_nodes}_{n_edges}_{seed}.npz")
    t0 = time.perf_counter()
    plan = spmv_mxu.load_plan(cache) if os.path.exists(cache) else None
    plan_cached = plan is not None and plan.n_nodes == n_nodes
    plan_build_s = 0.0
    meta_path = cache + ".meta.json"
    if not plan_cached:
        t1 = time.perf_counter()
        plan = spmv_mxu.build_plan(src, dst, None, n_nodes)
        plan_build_s = time.perf_counter() - t1
        try:
            spmv_mxu.save_plan(plan, cache)
            with open(meta_path, "w") as f:
                json.dump({"plan_build_fresh_s": plan_build_s}, f)
        except OSError:
            pass
    plan_s = time.perf_counter() - t0
    # the fresh-build cost is a real number even when this run hit the
    # cache: report the persisted measurement from the run that built it
    plan_build_fresh_s = plan_build_s
    if plan_cached and os.path.exists(meta_path):
        try:
            with open(meta_path) as f:
                plan_build_fresh_s = float(
                    json.load(f)["plan_build_fresh_s"])
        except (OSError, ValueError, KeyError):
            pass

    # O(delta) refresh cost: the side-plan for a 100k-edge topology
    # change (the streaming-ingest path; full replan no longer needed —
    # ops/pagerank._try_delta_plan, tests/test_plan_delta_e2e.py)
    t1 = time.perf_counter()
    drng = np.random.default_rng(1)
    spmv_mxu.build_delta_plan(
        plan, drng.integers(0, n_nodes, 100_000),
        (drng.random(100_000) ** 2 * n_nodes).astype(np.int64))
    plan_delta_build_s = time.perf_counter() - t1

    t0 = time.perf_counter()
    # bf16 routing through the Benes (f32 accumulation): validated to
    # preserve exact top-100 order on this graph; the overlap check below
    # re-verifies every run
    run = spmv_mxu.make_pagerank_kernel(plan, route_dtype=jnp.bfloat16)
    transfer_s = time.perf_counter() - t0  # blob pack + device_put
    t0 = time.perf_counter()
    # uniform start computed on-device (None): saves one 33MB transfer
    # compile + warm (excluded); 1-element host transfer forces completion
    rank, err, iters = run(None, jnp.float32(DAMPING), ITERATIONS,
                           jnp.float32(0.0))
    _ = float(rank[0])
    warm_s = time.perf_counter() - t0

    def once():
        out = run(None, jnp.float32(DAMPING), ITERATIONS, jnp.float32(0.0))
        _ = float(out[0][0])
        return out
    # best-of-3 mirrors the CPU baseline's timing
    (rank, err, iters), elapsed = best_timed(once)
    assert int(iters) == ITERATIONS, f"expected {ITERATIONS}, ran {int(iters)}"
    ranks = np.asarray(rank)[plan.out_relabel]
    np.savez(out_path, ranks=ranks, elapsed=elapsed,
             export_s=plan_s + transfer_s + warm_s,
             build_s=plan_s, transfer_s=transfer_s,
             plan_build_s=plan_build_s, plan_cached=plan_cached,
             plan_build_fresh_s=plan_build_fresh_s,
             plan_delta_build_s=plan_delta_build_s,
             warm_s=warm_s,
             platform=jax.devices()[0].platform)


def stage_pagerank(n_nodes, n_edges, seed, out_path):
    """CSR export + device PageRank via the RESUMABLE partition-centric
    entry point (mesh-of-1 degeneracy of the sharded path): the loop
    carry checkpoints to host every BENCH_CHECKPOINT_EVERY iterations,
    so a device fault mid-stage resumes instead of restarting — the
    same path the kernel server serves. Writes ranks + timings."""
    from memgraph_tpu.ops import csr
    from memgraph_tpu.parallel import analytics
    from memgraph_tpu.parallel.mesh import get_mesh_context
    import jax

    ckpt_every = int(os.environ.get("BENCH_CHECKPOINT_EVERY", "25"))
    src, dst = generate_graph(n_nodes, n_edges, seed)
    t0 = time.perf_counter()
    graph = csr.from_coo(src, dst, n_nodes=n_nodes)
    build_s = time.perf_counter() - t0
    ctx = get_mesh_context(1)
    t0 = time.perf_counter()
    # partition-centric blocking + device placement (cached on the graph)
    csr.shard_csr(graph, ctx, by="src")
    transfer_s = time.perf_counter() - t0
    export_s = build_s + transfer_s

    def run():
        # tol=-1 pins the run to exactly ITERATIONS iterations (f32 err
        # can legitimately reach 0.0, so tol=0 could stop early)
        return analytics.pagerank_mesh(
            graph, ctx, damping=DAMPING, max_iterations=ITERATIONS,
            tol=-1.0, checkpoint_every=ckpt_every)

    # compile + warm up (excluded from timing); host-transfer forces
    # completion — block_until_ready is unreliable on the tunneled platform
    # mgstat (r14): the stage accumulator rides the whole device extent,
    # so the record carries the SAME per-stage attribution PROFILE shows
    # (transfer / compile-fold / iterate), measured by the product hooks
    # rather than by bench-side stopwatches alone.
    from memgraph_tpu.observability import stats as mgstats
    acc = mgstats.StageAccumulator()
    with mgstats.collecting_stages(acc):
        t0 = time.perf_counter()
        rank, err, iters = run()
        _ = float(rank[0])
        warm_s = time.perf_counter() - t0

        def once():
            out = run()
            _ = float(out[0][0])  # host sync
            return out
        (rank, err, iters), elapsed = best_timed(once)
    assert int(iters) == ITERATIONS, f"expected {ITERATIONS}, ran {int(iters)}"
    np.savez(out_path, ranks=np.asarray(rank[:n_nodes]),
             elapsed=elapsed, export_s=export_s,
             build_s=build_s, transfer_s=transfer_s, warm_s=warm_s,
             mgstat_stages=json.dumps(acc.snapshot()),
             platform=jax.devices()[0].platform)


SEMIRING_ITERATIONS = 20


def stage_semiring(n_nodes, n_edges, seed, out_path):
    """Semiring-core sweep (r10): pagerank through ops/semiring.py at
    f32 AND bf16 (same dispatch the product serves), plus BFS via the
    min-plus generic mesh kernel — routed through the RESIDENT kernel
    server's `semiring` op when a daemon is reachable (the graph ships
    once under a graph_key; timed calls pay socket + device only), else
    in-process.  Writes per-precision timings + top-100 f32/bf16
    overlap so the record carries rank-order-preservation evidence."""
    import jax
    src, dst = generate_graph(n_nodes, n_edges, seed)
    client = None
    resident = False
    try:
        from memgraph_tpu.server.kernel_server import ensure_server
        client = ensure_server()
        resident = True
    except Exception as e:  # noqa: BLE001 — environmental: fall back
        log(f"  resident kernel server unavailable for semiring "
            f"sweep ({e}); running in-process")
    results = {}
    if client is not None:
        key = f"sem_{n_nodes}_{n_edges}_{seed}"
        # warm: ship the graph + compile (excluded from timing)
        client.semiring("pagerank", src=src, dst=dst, n_nodes=n_nodes,
                        graph_key=key, max_iterations=2, tol=-1.0)
        for prec in ("f32", "bf16"):
            def once(prec=prec):
                _h, out = client.semiring(
                    "pagerank", graph_key=key, precision=prec,
                    max_iterations=SEMIRING_ITERATIONS, tol=-1.0)
                return out["ranks"]
            ranks, elapsed = best_timed(once, budget_s=40.0)
            results[prec] = (np.asarray(ranks), elapsed)

        def bfs_once():
            h, _out = client.semiring("bfs", graph_key=key, source=0)
            return h["iters"]
        _, bfs_elapsed = best_timed(bfs_once, budget_s=20.0)
        platform = client.health().get("platform") or \
            jax.devices()[0].platform
        client.close()
    else:
        from memgraph_tpu.ops import csr
        from memgraph_tpu.ops.pagerank import pagerank
        from memgraph_tpu.parallel import analytics
        from memgraph_tpu.parallel.mesh import get_mesh_context
        graph = csr.from_coo(src, dst, n_nodes=n_nodes)
        for prec in ("f32", "bf16"):
            pagerank(graph, max_iterations=2, tol=-1.0, precision=prec)

            def once(prec=prec):
                out = pagerank(graph, max_iterations=SEMIRING_ITERATIONS,
                               tol=-1.0, precision=prec)
                _ = float(np.asarray(out[0])[0])
                return np.asarray(out[0])
            ranks, elapsed = best_timed(once, budget_s=40.0)
            results[prec] = (ranks, elapsed)
        ctx1 = get_mesh_context(1)
        analytics.bfs_mesh(graph, ctx1, 0)          # warm

        def bfs_once():
            return analytics.bfs_mesh(graph, ctx1, 0)[1]
        _, bfs_elapsed = best_timed(bfs_once, budget_s=20.0)
        platform = jax.devices()[0].platform
    f32_ranks, f32_s = results["f32"]
    bf16_ranks, bf16_s = results["bf16"]
    top100 = lambda r: set(np.argsort(-r)[:100].tolist())  # noqa: E731
    overlap = len(top100(f32_ranks[:n_nodes]) & top100(bf16_ranks[:n_nodes]))
    np.savez(out_path, f32_s=f32_s, bf16_s=bf16_s, bfs_s=bfs_elapsed,
             overlap=overlap, platform=platform, resident=resident)


#: fixed sweep count for the tier stage — convergence is the smoke's
#: and the test suite's territory; the bench wants a stable edges/s +
#: overlap measurement over a known number of full-graph sweeps
TIER_ITERATIONS = 20


def stage_tier(n_nodes, n_edges, seed, out_path):
    """Out-of-core streamed tier (r21 mgtier): PageRank over a
    host-pinned TierCSR — compressed edge blocks stream H2D
    double-buffered against the previous block's SpMV fold while the
    rank vector stays device-resident. Records the measured serial
    transfer/compute split (first iteration runs the blocks serially
    to price both sides), the overlapped-iteration wall time and the
    hidden-transfer fraction the BASELINE.json tier_overlap envelope
    defends, plus the bf16/int8 wire-compression ratios vs raw COO."""
    import jax
    from memgraph_tpu.ops import tier as mgtier
    from memgraph_tpu.parallel.distributed import pagerank_streamed
    rng = np.random.default_rng(seed)
    src = rng.integers(0, n_nodes, n_edges).astype(np.int64)
    dst = rng.integers(0, n_nodes, n_edges).astype(np.int64)
    w = (rng.random(n_edges) + 0.1).astype(np.float32)
    # enough blocks that the double-buffer schedule has real work to
    # hide even when the bench graph fits the default 32 MiB budget
    n_blocks = max(8, mgtier.plan_blocks(n_nodes, n_edges, "f32",
                                         mgtier.block_bytes_budget()))
    tier = mgtier.plan_tier(src, dst, w, n_nodes, precision="f32",
                            n_blocks=n_blocks)
    pagerank_streamed(tier, max_iterations=2, tol=-1.0)       # warm
    stats = {}
    t0 = time.perf_counter()
    ranks, _err, iters = pagerank_streamed(
        tier, max_iterations=TIER_ITERATIONS, tol=-1.0, stats=stats)
    elapsed = time.perf_counter() - t0
    _ = float(np.asarray(ranks)[0])
    ratios = {}
    for prec in ("bf16", "int8"):
        tp = mgtier.plan_tier(src, dst, w, n_nodes, precision=prec,
                              n_blocks=n_blocks)
        ratios[prec] = (sum(b.raw_nbytes for b in tp.blocks)
                        / sum(b.nbytes for b in tp.blocks))
    np.savez(out_path, platform=jax.devices()[0].platform,
             elapsed=elapsed, iters=iters, n_blocks=tier.n_blocks,
             serial_transfer_s=stats.get("serial_transfer_s") or 0.0,
             serial_compute_s=stats.get("serial_compute_s") or 0.0,
             hidden=stats.get("transfer_hidden_fraction") or 0.0,
             overlap_iter_s=stats.get("overlap_iter_s_mean") or 0.0,
             wire_bytes=stats.get("wire_bytes_per_sweep", 0),
             raw_bytes=stats.get("raw_bytes_per_sweep", 0),
             ratio_bf16=ratios["bf16"], ratio_int8=ratios["int8"])


#: churn fraction for the delta stage — 0.5% of the edge set in ONE
#: committed remove+add transaction (half the envelope's ≤1% ceiling;
#: representative of a heavy OLTP burst between two CALLs)
DELTA_CHURN = float(os.environ.get("BENCH_DELTA_CHURN", "0.005"))


def stage_delta(n_nodes, n_edges, seed, out_path):
    """mgdelta (r19): commit-to-fresh-result vs cold full rebuild, plus
    the streaming-ingest-while-querying scenario the bench never
    covered.

    Part 1 — resident delta speedup at full size: a ResidentGraph holds
    the graph device-side with a converged pagerank solution; a ≤1%
    edge churn then goes through BOTH paths:
      cold  = from_coo (native CSR build) + shard_edges (global
              lexsort) + device placement + cold fixpoint — the
              CONSERVATIVE cold baseline (the real product cold path
              additionally pays the Python MVCC export walk);
      delta = change-log diff (diff_changed_coo) + EdgeDelta splice of
              the resident layout (O(delta + affected rows)) + re-place
              + warm-started fixpoint at the SAME tol.
    delta_speedup = cold_s / delta_s feeds the BASELINE.json
    ``delta_speedup`` envelope (perf_gate.check_delta).

    Part 2 — streaming ingest while querying (small scale): a writer
    thread feeds edge batches through the storage bulk lane while a
    query loop serves commit-then-CALL pagerank through GraphCache +
    LocalWarmPool; records fresh-result latency percentiles and
    delta-apply throughput.
    """
    import jax
    from memgraph_tpu.ops import delta as D
    from memgraph_tpu.ops.csr import export_csr, shard_edges
    from memgraph_tpu.parallel.distributed import \
        pagerank_partition_centric
    from memgraph_tpu.parallel.mesh import get_mesh_context
    from memgraph_tpu.storage import InMemoryStorage

    tol = 1e-6
    ctx = get_mesh_context(1)
    rng = np.random.default_rng(seed + 1)

    # real storage at full size (setup, untimed): the cold path below
    # is the PRODUCT's commit-then-CALL — MVCC export walk + CSR build
    # + partition blocking + cold fixpoint — not a synthetic stand-in
    big = InMemoryStorage()
    acc = big.access()
    verts, _ = acc.batch_insert(
        vertices=[((), {}) for _ in range(n_nodes)])
    et_big = big.edge_type_mapper.name_to_id("E")
    B = 500_000
    for lo in range(0, n_edges, B):
        hi = min(lo + B, n_edges)
        a = rng.integers(0, n_nodes, hi - lo)
        b = (rng.random(hi - lo) ** 2 * n_nodes).astype(np.int64)
        acc.batch_insert(edges=[
            (et_big, verts[int(x)], verts[int(y)], None)
            for x, y in zip(a, b)])
    acc.commit()
    log(f"  delta stage: storage built ({n_nodes:,} nodes, "
        f"{n_edges:,} edges)")

    # resident generation at v0 (setup, untimed): export + sharded
    # variant + a converged solution to warm-start from
    acc0 = big.access()
    v0 = acc0.topology_snapshot
    g0 = export_csr(acc0, to_device=False)
    acc0.abort()
    gen = D.ResidentGraph("bench", v0, g0)
    scsr0 = gen.ensure_sharded(ctx, by="src")
    r0, _, it_cold0 = pagerank_partition_centric(scsr0, ctx, tol=tol)
    gen.note_solution("pagerank", ("p",), np.asarray(r0))

    # the ≤1% churn, ONE committed transaction: half removals of
    # existing edges, half fresh adds between existing vertices
    k = max(1, int(n_edges * DELTA_CHURN / 2))
    wacc = big.access()
    edge_gids = list(big._edges.keys())
    for gid in rng.choice(len(edge_gids), k, replace=False):
        ea = wacc.find_edge(edge_gids[int(gid)])
        if ea is not None:
            wacc.delete_edge(ea)
    a = rng.integers(0, n_nodes, k)
    b = (rng.random(k) ** 2 * n_nodes).astype(np.int64)
    wacc.batch_insert(edges=[
        (et_big, verts[int(x)], verts[int(y)], None)
        for x, y in zip(a, b)])
    wacc.commit()
    v1 = big.topology_version

    # COLD commit-then-CALL (timed end to end): the pre-mgdelta path
    t0 = time.perf_counter()
    acc_c = big.access()
    g_c = export_csr(acc_c, to_device=False)
    acc_c.abort()
    scsr_cold = shard_edges(*g_c.host_coo, n_nodes, ctx.n_shards,
                            by="src").to_device(ctx)
    rc_ranks, _, it_cold = pagerank_partition_centric(scsr_cold, ctx,
                                                      tol=tol)
    cold_s = time.perf_counter() - t0

    # DELTA commit-then-CALL (timed end to end): change log -> O(delta)
    # incident read -> diff -> resident splice -> warm-started fixpoint
    t0 = time.perf_counter()
    acc_d = big.access()
    changed = big.changes_between(v0, v1)
    assert isinstance(changed, frozenset), changed
    inc = D.incident_from_storage(acc_d, gen.gid_to_idx, changed)
    changed_idx = [gen.gid_to_idx[g] for g in changed
                   if g in gen.gid_to_idx]
    d = D.diff_incident(gen.coo, changed_idx, inc[0], inc[1], inc[2],
                        gen.n_nodes, v0, v1)
    acc_d.abort()
    t_diff = time.perf_counter() - t0
    applied = gen.apply(d, ctx)
    t_apply = time.perf_counter() - t0 - t_diff
    x0, _ = gen.warm_x0("pagerank", ("p",))
    scsr_new = gen.ensure_sharded(ctx, by="src")
    rw_ranks, _, it_warm = pagerank_partition_centric(
        scsr_new, ctx, tol=tol, x0=x0)
    delta_s = time.perf_counter() - t0
    # freshness contract: same tol, residual-equivalent result
    linf = float(np.abs(np.asarray(rc_ranks)
                        - np.asarray(rw_ranks)).max())
    del big, verts, g_c, g0, scsr_cold

    # part 2: streaming ingest while querying (bulk lane feeding
    # commits while commit-then-CALL pagerank serves fresh results)
    import threading as _threading
    from memgraph_tpu.ops.csr import GLOBAL_GRAPH_CACHE
    st = InMemoryStorage()
    sn, se = 20_000, 80_000
    acc = st.access()
    et = st.edge_type_mapper.name_to_id("E")
    verts, _ = acc.batch_insert(vertices=[((), {}) for _ in range(sn)])
    srng = np.random.default_rng(seed + 2)
    acc.batch_insert(edges=[
        (et, verts[a], verts[b], None)
        for a, b in zip(srng.integers(0, sn, se),
                        srng.integers(0, sn, se))])
    acc.commit()
    pool = D.LocalWarmPool()
    stop = _threading.Event()
    ingested = [0]

    def writer():
        while not stop.is_set():
            w_acc = st.access()
            batch = [(et, verts[int(a)], verts[int(b)], None)
                     for a, b in zip(srng.integers(0, sn, 50),
                                     srng.integers(0, sn, 50))]
            w_acc.batch_insert(edges=batch)
            w_acc.commit()
            ingested[0] += len(batch)
            time.sleep(0.02)

    wt = _threading.Thread(target=writer, daemon=True)
    latencies = []
    warm_iters = []
    t_stream = time.perf_counter()
    wt.start()
    try:
        from memgraph_tpu.ops.pagerank import pagerank as _pr
        while time.perf_counter() - t_stream < 6.0:
            q0 = time.perf_counter()
            q_acc = st.access()
            try:
                g = GLOBAL_GRAPH_CACHE.get(q_acc)
                v = q_acc.topology_snapshot
                cached, x0s = pool.prepare(st, g, v, "pagerank",
                                           ("p",))
                if cached is None:
                    ranks, _, its = _pr(g, tol=1e-5, x0=x0s)
                    pool.store(st, g, v, "pagerank", ("p",),
                               np.asarray(ranks))
            finally:
                q_acc.abort()
            latencies.append(time.perf_counter() - q0)
            if cached is None and x0s is not None:
                warm_iters.append(int(its))
    finally:
        stop.set()
        wt.join(timeout=5)
    stream_s = time.perf_counter() - t_stream
    lat = np.asarray(sorted(latencies))

    np.savez(
        out_path, cold_s=cold_s, delta_s=delta_s, diff_s=t_diff,
        apply_s=t_apply, applied=bool(applied),
        delta_edges=d.n_delta, it_cold=it_cold, it_warm=it_warm,
        it_cold0=it_cold0, linf=linf,
        stream_queries=len(latencies),
        stream_commits_edges=ingested[0],
        stream_seconds=stream_s,
        fresh_latency_p50_ms=float(lat[len(lat) // 2] * 1e3)
        if len(lat) else 0.0,
        fresh_latency_p95_ms=float(lat[int(len(lat) * 0.95)] * 1e3)
        if len(lat) else 0.0,
        warm_queries=len(warm_iters),
        warm_iters_mean=float(np.mean(warm_iters))
        if warm_iters else 0.0,
        platform=jax.devices()[0].platform)


def stage_stream(n_records, batch_size, seed, out_path):
    """mgstream (r17): sustained exactly-once streaming ingestion.

    Host-side (no device): the whole stage measures the transactional
    ingest path — FILE source poll → transform → per-batch transaction
    carrying the WAL OP_STREAM_OFFSET record → consumer ack. Three
    phases:

      A  backlog drain: n_records pre-written JSONL lines through one
         stream -> sustained records/s end-to-end (the headline floor
         BASELINE.json ``stream_ingest`` enforces on every host);
      B  always-fresh reads under live ingest: a producer appends at a
         fixed rate while a reader loop times count() queries against
         the same storage -> fresh-read latency percentiles (reads must
         stay cheap and monotone while the consumer commits);
      C  consumer kill + cold restart mid-ingest: records appended
         while dead must drain after restart with ZERO duplicates (the
         recovered offset dedups) — exactly_once feeds the gate.
    """
    import shutil
    import tempfile
    import threading as _threading

    from memgraph_tpu.query import streams as S
    from memgraph_tpu.query.interpreter import (Interpreter,
                                                InterpreterContext)
    from memgraph_tpu.storage import InMemoryStorage, StorageConfig
    from memgraph_tpu.storage.durability.recovery import (recover,
                                                          wire_durability)
    from memgraph_tpu.storage.kvstore import KVStore

    workdir = tempfile.mkdtemp(prefix="bench-stream-")
    feed = os.path.join(workdir, "feed.jsonl")
    storage = InMemoryStorage(StorageConfig(
        durability_dir=os.path.join(workdir, "data"), wal_enabled=True))
    recover(storage)
    wal = wire_durability(storage)
    ictx = InterpreterContext(storage)
    ictx.kvstore = KVStore(os.path.join(workdir, "kv.db"))
    interp = Interpreter(ictx, system=True)

    def transform(batch):
        return [{"query": "CREATE (:Ev {id: $id})",
                 "parameters": {"id": json.loads(
                     m.payload_str())["id"]}}
                for m in batch]

    S.TRANSFORMATIONS["bench_stream"] = transform

    def count():
        _c, rows, _s = interp.execute("MATCH (e:Ev) RETURN count(e)")
        return rows[0][0]

    def produce(ids):
        with open(feed, "a", encoding="utf-8") as f:
            for i in ids:
                f.write(json.dumps({"id": int(i)}) + "\n")

    def wait_count(target, timeout=120.0):
        deadline = time.time() + timeout
        while time.time() < deadline and count() < target:
            time.sleep(0.02)
        return count() >= target

    try:
        spec = S.StreamSpec(
            name="bench", kind="file", topics=[feed],
            transform="bench_stream", batch_size=batch_size,
            batch_interval_sec=0.02)
        # phase A: drain a pre-written backlog, timed end to end
        produce(range(n_records))
        stream = S.Stream(spec, ictx)
        t0 = time.perf_counter()
        stream.start()
        drained = wait_count(n_records)
        drain_s = time.perf_counter() - t0
        if not drained:
            raise RuntimeError(
                f"backlog never drained: {count()}/{n_records}")

        # phase B: fresh reads while a producer keeps appending
        stop = _threading.Event()
        produced_b = [0]

        def producer():
            i = n_records
            while not stop.is_set():
                produce([i])
                i += 1
                produced_b[0] += 1
                time.sleep(0.005)

        pt = _threading.Thread(target=producer, daemon=True)
        read_lat = []
        last = -1
        monotone = True
        pt.start()
        t_b = time.perf_counter()
        try:
            while time.perf_counter() - t_b < 4.0:
                q0 = time.perf_counter()
                c = count()
                read_lat.append(time.perf_counter() - q0)
                if c < last:
                    monotone = False
                last = c
        finally:
            stop.set()
            pt.join(timeout=5)

        # phase C: kill mid-ingest, append while dead, cold restart
        total_b = n_records + produced_b[0]
        stream.kill()
        produce(range(total_b, total_b + batch_size * 3))
        total = total_b + batch_size * 3
        stream2 = S.Stream(spec, ictx)
        t_r = time.perf_counter()
        stream2.start()
        recovered = wait_count(total)
        recovery_s = time.perf_counter() - t_r
        stream2.stop()
        # exactly-once: every id exactly once, nothing extra
        _c, rows, _s = interp.execute(
            "MATCH (e:Ev) WITH e.id AS i, count(*) AS c "
            "WHERE c > 1 RETURN count(*)")
        dups = rows[0][0]
        exactly_once = recovered and dups == 0 and count() == total

        lat = np.asarray(sorted(read_lat))
        np.savez(
            out_path,
            records_per_sec=n_records / max(drain_s, 1e-9),
            drain_s=drain_s, n_records=n_records,
            batch_size=batch_size,
            fresh_reads=len(read_lat),
            fresh_read_p50_ms=float(lat[len(lat) // 2] * 1e3)
            if len(lat) else 0.0,
            fresh_read_p95_ms=float(lat[int(len(lat) * 0.95)] * 1e3)
            if len(lat) else 0.0,
            reads_monotone=monotone,
            live_ingested=produced_b[0],
            recovery_drain_s=recovery_s,
            duplicates=int(dups), total=total,
            exactly_once=bool(exactly_once),
            wal_offset=int(storage.stream_offsets.get("bench", 0)),
            platform="host")
    finally:
        S.TRANSFORMATIONS.pop("bench_stream", None)
        wal.close()
        shutil.rmtree(workdir, ignore_errors=True)


def stage_latency(out_path):
    """CALL-to-first-record latency through the module/CSR-cache path.

    Cold = a FRESH client process's first CALL on a new graph. With the
    resident kernel server (memgraph_tpu/server/kernel_server.py) the
    client no longer pays the ~1.5s per-process device-executable load
    the tunneled platform charges — the daemon holds the runtime, the
    client pays export + one socket round-trip + device compute."""
    from memgraph_tpu.storage import InMemoryStorage, StorageConfig, StorageMode
    from memgraph_tpu.ops.csr import GraphCache, export_csr
    from memgraph_tpu.ops.pagerank import pagerank

    storage = InMemoryStorage(StorageConfig(
        storage_mode=StorageMode.IN_MEMORY_ANALYTICAL))
    rng = np.random.default_rng(3)
    n, e = 20_000, 100_000
    acc = storage.access()
    et = storage.edge_type_mapper.name_to_id("E")
    vs = [acc.create_vertex() for _ in range(n)]
    for s, d in zip(rng.integers(0, n, e), rng.integers(0, n, e)):
        acc.create_edge(vs[s], vs[d], et)
    acc.commit()

    resident = False
    client = None
    try:
        from memgraph_tpu.server.kernel_server import ensure_server, \
            KernelClient
    except Exception:  # noqa: BLE001 — environmental -> quiet fallback
        ensure_server = None
    if ensure_server is not None:
        # reuse the resident daemon when it is already up; one retry on
        # failure — a transient spawn race must not demote the whole
        # latency stage to the non-resident fallback. Timing rides the
        # shared RetryPolicy (no ad-hoc sleep constants).
        from memgraph_tpu.utils.retry import RetryPolicy
        for attempt in RetryPolicy(base_delay=2.0, factor=1.0,
                                   jitter=0.0, max_retries=1).attempts():
            try:
                client = ensure_server()
                break
            except RuntimeError as e:
                # daemon died during init: a real regression — say so
                # loudly (the bench still falls back so a number is
                # always produced)
                log(f"  RESIDENT KERNEL SERVER DIED DURING INIT "
                    f"(attempt {attempt + 1}): {e}")
            except Exception as e:  # noqa: BLE001 — environmental
                log(f"  resident kernel server unavailable "
                    f"(attempt {attempt + 1}): {e}")
    if client is not None:
        # steady-state server: shape-bucket kernels already compiled
        # (a production daemon has served before); measure a NEW graph
        wsrc = rng.integers(0, n, e)
        wdst = rng.integers(0, n, e)
        client.pagerank(src=wsrc, dst=wdst, n_nodes=n, graph_key="warmup",
                        max_iterations=100, tol=1e-6)
        sock = client.socket_path
        client.close()

        acc2 = storage.access()
        t0 = time.perf_counter()
        c2 = KernelClient(sock)                      # fresh client
        g = export_csr(acc2, to_device=False)        # host-side export
        ranks, _, _ = c2.pagerank(
            src=g.host_coo[0], dst=g.host_coo[1], n_nodes=g.n_nodes,
            graph_key="bench", max_iterations=100, tol=1e-6)
        _ = (int(g.node_gids[0]), float(ranks[0]))
        cold = time.perf_counter() - t0
        t0 = time.perf_counter()
        ranks, _, _ = c2.pagerank(graph_key="bench",
                                  max_iterations=100, tol=1e-6)
        _ = float(ranks[0])
        warm = time.perf_counter() - t0
        c2.close()
        acc2.abort()
        resident = True
    else:
        cache = GraphCache()
        acc = storage.access()
        t0 = time.perf_counter()
        g = cache.get(acc)
        ranks, _, _ = pagerank(g, max_iterations=100, tol=1e-6)
        _ = (int(g.node_gids[0]), float(ranks[0]))
        cold = time.perf_counter() - t0
        t0 = time.perf_counter()
        g = cache.get(acc)
        ranks, _, _ = pagerank(g, max_iterations=100, tol=1e-6)
        _ = float(ranks[0])
        warm = time.perf_counter() - t0
        acc.abort()
    np.savez(out_path, cold=cold, warm=warm, resident=resident)


# --------------------------------------------------------------------------
# orchestrator
# --------------------------------------------------------------------------

# the stage subprocess currently in flight, so the watchdog can kill it
# before emitting (an orphan would keep hammering the device tunnel)
_CURRENT_CHILD = None


def _emit_and_exit():
    child = _CURRENT_CHILD
    if child is not None and child.poll() is None:
        try:
            child.kill()
        except OSError:
            pass
    print(json.dumps(PARTIAL))
    sys.stdout.flush()
    os._exit(0)


def _arm_watchdog(seconds=MASTER_TIMEOUT_SEC):
    import signal

    def on_alarm(signum, frame):
        PARTIAL["extra"].setdefault(
            "error", "bench watchdog fired (partial result)")
        PARTIAL["extra"]["watchdog_fired_after_s"] = seconds
        _emit_and_exit()

    signal.signal(signal.SIGALRM, on_alarm)
    signal.alarm(seconds)


def _run_stage(args, env, timeout):
    """Run this script as a subprocess stage. Returns (rc, stdout) or
    (None, None) on timeout (the child is killed)."""
    global _CURRENT_CHILD
    cmd = [sys.executable, os.path.abspath(__file__)] + args
    p = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                         stderr=sys.stderr)
    _CURRENT_CHILD = p
    try:
        out, _ = p.communicate(timeout=timeout)
        return p.returncode, out
    except subprocess.TimeoutExpired:
        p.kill()
        try:
            p.communicate(timeout=10)
        except subprocess.TimeoutExpired:
            pass
        return None, None
    finally:
        _CURRENT_CHILD = None


def _stage_env(platform=None):
    env = dict(os.environ)
    if platform is not None:
        # JAX_PLATFORMS alone is NOT enough: /root/.axon_site pre-inits jax
        # in subprocesses, so the stage must also jax.config.update() — it
        # reads this variable (see __main__ below)
        env["JAX_PLATFORMS"] = platform
        env["BENCH_JAX_PLATFORM"] = platform
    return env


def main():
    _arm_watchdog()
    t_bench = time.perf_counter()

    log(f"generating {N_EDGES:,}-edge graph ...")
    src, dst = generate_graph()

    log("CPU baseline (scipy CSR power iteration) ...")
    cpu_ranks, cpu_time = cpu_pagerank(src, dst, N_NODES)
    cpu_eps = N_EDGES * ITERATIONS / cpu_time
    log(f"  {cpu_time:.3f}s -> {cpu_eps:,.0f} edges/s")
    PARTIAL["extra"] = {"cpu_seconds_50iter": round(cpu_time, 4),
                        "error": "device stages did not complete"}

    log("probing device (subprocess) ...")
    t_probe = time.perf_counter()
    device_ok = False
    probe_server_health = None
    probe_outcome = "probe_never_ran"
    for attempt in range(2):
        rc, out = _run_stage(["--stage", "probe"], _stage_env(),
                             PROBE_TIMEOUT_SEC)
        device_ok = rc == 0
        probe_outcome = _classify_probe(rc)
        log(f"  probe attempt {attempt + 1}: rc={rc} ok={device_ok} "
            f"{(out or b'').decode(errors='replace').strip()}")
        if device_ok:
            break
        # BENCH_r05 scored a CPU fallback because ONE flaky probe failed;
        # a single retry after a short pause is cheap insurance
        time.sleep(3)
    if not device_ok:
        # second opinion from the resident kernel server's health plane:
        # the daemon holds a live device runtime, so its typed probe is
        # authoritative — a flaky subprocess probe must not demote a
        # scored run to CPU while the resident device is demonstrably
        # fine (BENCH_r05's failure mode)
        health, probe_reply = _resident_probe()
        if health is None:
            probe_outcome += "+no_resident_server"
        elif health.get("wedged"):
            probe_outcome += "+resident_server_wedged"
        elif probe_reply is None:
            probe_outcome += "+resident_probe_unanswered"
        elif probe_reply.get("ok"):
            device_ok = True
            probe_outcome += "+resident_probe_ok"
            log("  subprocess probe failed but the RESIDENT kernel "
                "server's device probe completed — using the device "
                f"ladder (platform={probe_reply.get('platform')})")
        else:
            probe_outcome += \
                f"+resident_probe_{probe_reply.get('outcome', 'failed')}"
        if health is not None:
            probe_server_health = {
                "wedged": bool(health.get("wedged")),
                "in_flight": health.get("in_flight"),
                "uptime_s": health.get("uptime_s"),
                "platform": health.get("platform"),
            }
            PARTIAL["extra"]["probe_server_health"] = probe_server_health
    PARTIAL["extra"]["probe_outcome"] = probe_outcome
    probe_s = time.perf_counter() - t_probe

    # fallback ladder: tunneled TPU at full size, TPU at 1M edges, then
    # jax-CPU at full size — the driver must always get a nonzero number
    ladder = []
    if device_ok:
        ladder.append(("axon", "pagerank_mxu", N_NODES, N_EDGES,
                       STAGE_TIMEOUT_SEC))
        ladder.append(("axon", "pagerank", N_NODES, N_EDGES,
                       STAGE_TIMEOUT_SEC))
        ladder.append(("axon", "pagerank", N_NODES // 10, N_EDGES // 10, 120))
    ladder.append(("cpu", "pagerank", N_NODES, N_EDGES, STAGE_TIMEOUT_SEC))

    result = None
    for platform, stage, n_nodes, n_edges, budget in ladder:
        remaining = MASTER_TIMEOUT_SEC - (time.perf_counter() - t_bench) - 15
        if remaining < 35:
            log("  out of time budget; stopping the ladder")
            break
        budget = min(budget, int(remaining))
        log(f"{stage} stage: platform={platform} edges={n_edges:,} "
            f"budget={budget}s ...")
        with tempfile.NamedTemporaryFile(suffix=".npz") as tf:
            rc, _ = _run_stage(
                ["--stage", stage, str(n_nodes), str(n_edges), "7",
                 tf.name], _stage_env(platform), budget)
            if rc != 0:
                log(f"  stage failed (rc={rc}); falling back")
                continue
            data = np.load(tf.name)
            result = {
                "platform": str(data["platform"]), "kernel": stage,
                "n_nodes": n_nodes, "n_edges": n_edges,
                "ranks": data["ranks"], "elapsed": float(data["elapsed"]),
                "export_s": float(data["export_s"]),
            }
            for key in ("plan_build_s", "plan_cached", "warm_s",
                        "plan_build_fresh_s", "plan_delta_build_s",
                        "build_s", "transfer_s"):
                if key in data.files:
                    result[key] = float(data[key])
            if "mgstat_stages" in data.files:
                try:
                    result["mgstat_stages"] = json.loads(
                        str(data["mgstat_stages"]))
                except (ValueError, TypeError):
                    pass
        break

    if result is None:
        PARTIAL["extra"]["error"] = ("all device stages failed/timed out; "
                                     "cpu baseline only")
        _emit_and_exit()

    eps = result["n_edges"] * ITERATIONS / result["elapsed"]
    log(f"  {result['elapsed']:.3f}s for {ITERATIONS} iterations "
        f"-> {eps:,.0f} edges/s on {result['platform']}")

    # acceptance: top-100 rank parity vs scipy on the same graph
    if result["n_edges"] == N_EDGES:
        base_ranks = cpu_ranks
        base_eps = cpu_eps
    else:  # fallback size: recompute baseline at that size for parity
        s2, d2 = generate_graph(result["n_nodes"], result["n_edges"], 7)
        base_ranks, base_time = cpu_pagerank(s2, d2, result["n_nodes"])
        base_eps = result["n_edges"] * ITERATIONS / base_time
    top_dev = set(np.argsort(-result["ranks"])[:100].tolist())
    top_cpu = set(np.argsort(-base_ranks)[:100].tolist())
    overlap = len(top_dev & top_cpu)
    log(f"top-100 overlap: {overlap}/100")

    # honesty contract (ROADMAP open item 5): the headline is only
    # non-degraded when it came from the real accelerator at full size.
    # A CPU fallback or a shrunken graph still yields a number, but one
    # every consumer (and tools/perf_gate.py) can see is not comparable.
    degraded = (result["platform"] == "cpu"
                or result["n_edges"] < N_EDGES)
    if degraded:
        log(f"  DEGRADED RUN: backend={result['platform']} "
            f"edges={result['n_edges']:,} — not a headline measurement")
    PARTIAL.update({
        "value": round(eps, 1),
        "vs_baseline": round(eps / base_eps, 3),
        "degraded": degraded,
        "backend": result["platform"],
    })
    PARTIAL["extra"] = {
        "device_platform": result["platform"],
        "kernel": result["kernel"],
        "bench_edges": result["n_edges"],
        "device_seconds_50iter": round(result["elapsed"], 4),
        "cpu_seconds_50iter": round(cpu_time, 4),
        "csr_export_transfer_s": round(result["export_s"], 2),
        "top100_overlap": overlap,
        "device_probe_ok": device_ok,
        # typed probe failure reason (ISSUE 7): a degraded record now
        # says WHY the device path was not used
        "probe_outcome": probe_outcome,
        # per-stage timings: where the wall clock actually went
        "stages": {
            "probe_s": round(probe_s, 2),
            "baseline_s": round(cpu_time, 2),
            "build_s": round(result.get("build_s", 0.0), 2),
            "transfer_s": round(result.get("transfer_s", 0.0), 2),
            "compile_warm_s": round(result.get("warm_s", 0.0), 2),
            "iterate_s": round(result["elapsed"], 4),
            # mgstat device attribution, measured by the product's own
            # stage hooks (the same numbers PROFILE shows): per stage
            # {"seconds", "count"} over the whole warm+timed extent
            "mgstat": result.get("mgstat_stages"),
        },
    }
    if probe_server_health is not None:
        PARTIAL["extra"]["probe_server_health"] = probe_server_health
    if "plan_build_s" in result:
        PARTIAL["extra"]["plan_build_s"] = round(result["plan_build_s"], 2)
        PARTIAL["extra"]["plan_cached"] = bool(result["plan_cached"])
        PARTIAL["extra"]["compile_warm_s"] = round(result["warm_s"], 2)
    for key in ("plan_build_fresh_s", "plan_delta_build_s"):
        if key in result:
            PARTIAL["extra"][key] = round(result[key], 2)

    # bulk-write fast lane: storage-level batch_insert throughput (r6).
    # Best-effort and cheap; the OLTP-grade end-to-end number lives in
    # benchmarks/mgbench.py (OLTP_r06.json load_records_per_sec).
    try:
        from memgraph_tpu.storage import InMemoryStorage as _IMS
        _st = _IMS()
        _lid = _st.label_mapper.name_to_id("U")
        _pid = _st.property_mapper.name_to_id("id")
        _t0 = time.perf_counter()
        _total = 0
        while time.perf_counter() - _t0 < 2.0:
            _acc = _st.access()
            _acc.batch_insert(vertices=[
                ((_lid,), {_pid: _total + i}) for i in range(10_000)])
            _acc.commit()
            _total += 10_000
        _rate = _total / (time.perf_counter() - _t0)
        PARTIAL["extra"]["bulk_insert_vertices_per_s"] = round(_rate, 1)
        log(f"bulk ingest (batch_insert): {_rate:,.0f} vertices/s")
    except Exception as _e:  # noqa: BLE001 — never block the north star
        log(f"bulk ingest stage skipped: {_e}")

    # semiring-core sweep (r10): pagerank via the core at f32/bf16 + BFS
    # via min-plus, honest per-sweep backend/degraded tagging; the perf
    # gate reads extra.semiring against the BASELINE.json ratio envelopes
    sem_nodes, sem_edges = N_NODES // 10, N_EDGES // 10
    remaining = MASTER_TIMEOUT_SEC - (time.perf_counter() - t_bench) - 10
    if remaining > 60:
        with tempfile.NamedTemporaryFile(suffix=".npz") as tf:
            # follow the platform the HEADLINE actually ran on — a probe
            # that succeeded on a CPU-only host must not send this stage
            # chasing a nonexistent accelerator
            sem_platform_env = "cpu" if result["platform"] == "cpu" \
                else "axon"
            rc, _ = _run_stage(
                ["--stage", "semiring", str(sem_nodes), str(sem_edges),
                 "7", tf.name],
                _stage_env(sem_platform_env),
                min(150, int(remaining)))
            if rc == 0:
                d = np.load(tf.name)
                f32_s = float(d["f32_s"])
                bf16_s = float(d["bf16_s"])
                sem_platform = str(d["platform"])
                PARTIAL["extra"]["semiring"] = {
                    "backend": sem_platform,
                    # the sweep's OWN honesty tag: a CPU run can never
                    # satisfy the on-device ratio envelopes
                    "degraded": sem_platform == "cpu",
                    "bench_edges": sem_edges,
                    "iterations": SEMIRING_ITERATIONS,
                    "f32_eps": round(
                        sem_edges * SEMIRING_ITERATIONS / f32_s, 1),
                    "bf16_eps": round(
                        sem_edges * SEMIRING_ITERATIONS / bf16_s, 1),
                    "bf16_speedup": round(f32_s / bf16_s, 3),
                    "bfs_minplus_s": round(float(d["bfs_s"]), 4),
                    "top100_overlap_f32_bf16": int(d["overlap"]),
                    "resident_kernel_server": bool(d["resident"]),
                }
                log(f"semiring sweep: f32 {f32_s:.3f}s bf16 {bf16_s:.3f}s "
                    f"(speedup {f32_s / bf16_s:.2f}x) on {sem_platform}")
            else:
                log(f"semiring sweep stage failed (rc={rc}); record "
                    "carries no extra.semiring")

    # mgdelta (r19): commit-to-fresh-result speedup + the
    # streaming-ingest-while-querying stage; feeds the BASELINE.json
    # delta_speedup envelope (perf_gate.check_delta). Honest per-stage
    # backend/degraded tagging like the semiring sweep.
    delta_nodes = int(os.environ.get("BENCH_DELTA_N_NODES", N_NODES))
    delta_edges = int(os.environ.get("BENCH_DELTA_N_EDGES", 3_000_000))
    remaining = MASTER_TIMEOUT_SEC - (time.perf_counter() - t_bench) - 10
    # the stage builds a REAL 1M-node storage through the bulk lane
    # (~90s) before it measures anything — with less than ~6 minutes
    # left it cannot finish, so skip LOUDLY instead of burning the
    # remaining budget on a record-less timeout (raise
    # BENCH_MASTER_TIMEOUT to include it in a default run)
    if remaining > 360:
        with tempfile.NamedTemporaryFile(suffix=".npz") as tf:
            delta_platform_env = "cpu" if result["platform"] == "cpu" \
                else "axon"
            rc, _ = _run_stage(
                ["--stage", "delta", str(delta_nodes),
                 str(delta_edges), "7", tf.name],
                _stage_env(delta_platform_env),
                min(420, int(remaining)))
            if rc == 0:
                d = np.load(tf.name)
                delta_platform = str(d["platform"])
                cold_s = float(d["cold_s"])
                delta_s = float(d["delta_s"])
                PARTIAL["extra"]["delta"] = {
                    "backend": delta_platform,
                    # own honesty tag, same contract as the semiring
                    # sweep: a CPU run can never satisfy the on-device
                    # delta_speedup envelope
                    "degraded": delta_platform == "cpu",
                    "n_nodes": delta_nodes,
                    "n_edges": delta_edges,
                    "churn": DELTA_CHURN,
                    "cold_rebuild_s": round(cold_s, 4),
                    "delta_refresh_s": round(delta_s, 4),
                    "delta_speedup": round(cold_s / max(delta_s, 1e-9),
                                           3),
                    "diff_s": round(float(d["diff_s"]), 4),
                    "apply_s": round(float(d["apply_s"]), 4),
                    "delta_edges": int(d["delta_edges"]),
                    "iters_cold": int(d["it_cold"]),
                    "iters_warm": int(d["it_warm"]),
                    "residual_linf": float(d["linf"]),
                    "streaming": {
                        "queries": int(d["stream_queries"]),
                        "ingested_edges": int(d["stream_commits_edges"]),
                        "seconds": round(float(d["stream_seconds"]), 2),
                        "fresh_latency_p50_ms": round(
                            float(d["fresh_latency_p50_ms"]), 2),
                        "fresh_latency_p95_ms": round(
                            float(d["fresh_latency_p95_ms"]), 2),
                        "warm_queries": int(d["warm_queries"]),
                        "warm_iters_mean": round(
                            float(d["warm_iters_mean"]), 2),
                    },
                }
                log(f"delta stage: cold {cold_s:.3f}s vs delta "
                    f"{delta_s:.3f}s (speedup "
                    f"{cold_s / max(delta_s, 1e-9):.2f}x) on "
                    f"{delta_platform}; streaming "
                    f"{int(d['stream_queries'])} fresh queries over "
                    f"{int(d['stream_commits_edges'])} ingested edges")
            else:
                log(f"delta stage failed (rc={rc}); record carries "
                    "no extra.delta")
    else:
        log(f"delta stage SKIPPED ({remaining:.0f}s left < 360s it "
            "needs); record carries no extra.delta")

    # mgtier (r21): out-of-core streamed edge blocks — the
    # double-buffered H2D-vs-SpMV overlap fraction plus the wire
    # compression ratios; feeds the BASELINE.json tier_overlap
    # envelope (perf_gate.check_tier)
    tier_nodes = int(os.environ.get("BENCH_TIER_N_NODES", N_NODES // 10))
    tier_edges = int(os.environ.get("BENCH_TIER_N_EDGES", N_EDGES // 10))
    remaining = MASTER_TIMEOUT_SEC - (time.perf_counter() - t_bench) - 10
    if remaining > 75:
        with tempfile.NamedTemporaryFile(suffix=".npz") as tf:
            tier_platform_env = "cpu" if result["platform"] == "cpu" \
                else "axon"
            rc, _ = _run_stage(
                ["--stage", "tier", str(tier_nodes), str(tier_edges),
                 "7", tf.name], _stage_env(tier_platform_env),
                min(180, int(remaining)))
            if rc == 0:
                d = np.load(tf.name)
                tier_platform = str(d["platform"])
                hidden = float(d["hidden"])
                PARTIAL["extra"]["tier"] = {
                    "backend": tier_platform,
                    # own honesty tag, same contract as the semiring /
                    # delta sweeps: a CPU host has no real H2D lane —
                    # its "overlap" is host-memcpy arithmetic and can
                    # never satisfy the on-device envelope
                    "degraded": tier_platform == "cpu",
                    "n_nodes": tier_nodes,
                    "n_edges": tier_edges,
                    "n_blocks": int(d["n_blocks"]),
                    "iterations": int(d["iters"]),
                    "streamed_s": round(float(d["elapsed"]), 4),
                    "eps": round(tier_edges * int(d["iters"])
                                 / max(float(d["elapsed"]), 1e-9), 1),
                    "serial_transfer_s": round(
                        float(d["serial_transfer_s"]), 4),
                    "serial_compute_s": round(
                        float(d["serial_compute_s"]), 4),
                    "overlap_iter_s_mean": round(
                        float(d["overlap_iter_s"]), 4),
                    "transfer_hidden_fraction": round(hidden, 4),
                    "wire_bytes_per_sweep": int(d["wire_bytes"]),
                    "raw_bytes_per_sweep": int(d["raw_bytes"]),
                    "wire_ratio_bf16": round(float(d["ratio_bf16"]), 3),
                    "wire_ratio_int8": round(float(d["ratio_int8"]), 3),
                }
                log(f"tier stage: {int(d['n_blocks'])} blocks, "
                    f"{hidden:.0%} of transfer hidden, wire bf16 "
                    f"{float(d['ratio_bf16']):.2f}x / int8 "
                    f"{float(d['ratio_int8']):.2f}x on {tier_platform}")
            else:
                log(f"tier stage failed (rc={rc}); record carries no "
                    "extra.tier")
    else:
        log(f"tier stage SKIPPED ({remaining:.0f}s left < 75s it "
            "needs); record carries no extra.tier")

    # mgstream (r17): sustained streaming ingestion — the supervised
    # FILE-stream consumer drains a pre-written backlog, serves fresh
    # reads under live ingest, then survives a mid-stream kill with
    # zero duplicates; feeds the BASELINE.json stream_ingest envelope
    # (perf_gate.check_stream). Host-side by construction (the plane is
    # the Cypher/WAL path, not a kernel) so it runs on every box.
    stream_records = int(os.environ.get("BENCH_STREAM_RECORDS", 2000))
    remaining = MASTER_TIMEOUT_SEC - (time.perf_counter() - t_bench) - 10
    if remaining > 40:
        with tempfile.NamedTemporaryFile(suffix=".npz") as tf:
            rc, _ = _run_stage(
                ["--stage", "stream", str(stream_records), "64", "7",
                 tf.name], _stage_env("cpu"), min(120, int(remaining)))
            if rc == 0:
                d = np.load(tf.name)
                PARTIAL["extra"]["stream_ingest"] = {
                    "backend": "host",
                    "n_records": int(d["n_records"]),
                    "batch_size": int(d["batch_size"]),
                    "records_per_sec": round(
                        float(d["records_per_sec"]), 1),
                    "drain_s": round(float(d["drain_s"]), 4),
                    "fresh_reads": int(d["fresh_reads"]),
                    "fresh_read_p50_ms": round(
                        float(d["fresh_read_p50_ms"]), 3),
                    "fresh_read_p95_ms": round(
                        float(d["fresh_read_p95_ms"]), 3),
                    "reads_monotone": bool(d["reads_monotone"]),
                    "live_ingested": int(d["live_ingested"]),
                    "recovery_drain_s": round(
                        float(d["recovery_drain_s"]), 4),
                    "duplicates": int(d["duplicates"]),
                    "total_ingested": int(d["total"]),
                    "exactly_once": bool(d["exactly_once"]),
                    "wal_offset": int(d["wal_offset"]),
                }
                log(f"stream stage: {float(d['records_per_sec']):.0f} "
                    f"records/s sustained, fresh-read p95 "
                    f"{float(d['fresh_read_p95_ms']):.2f}ms, kill+"
                    f"restart exactly_once={bool(d['exactly_once'])} "
                    f"({int(d['duplicates'])} dups)")
            else:
                log(f"stream stage failed (rc={rc}); record carries "
                    "no extra.stream_ingest")
    else:
        log(f"stream stage SKIPPED ({remaining:.0f}s left < 40s it "
            "needs); record carries no extra.stream_ingest")

    # CALL-to-first-record latency (best-effort; never blocks the result)
    remaining = MASTER_TIMEOUT_SEC - (time.perf_counter() - t_bench) - 10
    if remaining > 45:
        with tempfile.NamedTemporaryFile(suffix=".npz") as tf:
            rc, _ = _run_stage(
                ["--stage", "latency", tf.name],
                _stage_env("axon" if device_ok else "cpu"),
                min(120, int(remaining)))
            if rc == 0:
                data = np.load(tf.name)
                PARTIAL["extra"]["call_to_first_record_cold_ms"] = round(
                    float(data["cold"]) * 1e3, 1)
                PARTIAL["extra"]["call_to_first_record_warm_ms"] = round(
                    float(data["warm"]) * 1e3, 1)
                if "resident" in data.files:
                    PARTIAL["extra"]["resident_kernel_server"] = bool(
                        data["resident"])

    _emit_and_exit()


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--stage":
        _plat = os.environ.get("BENCH_JAX_PLATFORM")
        if _plat:
            import jax
            jax.config.update("jax_platforms", _plat)
        stage = sys.argv[2]
        if stage == "probe":
            stage_probe()
        elif stage == "pagerank":
            stage_pagerank(int(sys.argv[3]), int(sys.argv[4]),
                           int(sys.argv[5]), sys.argv[6])
        elif stage == "pagerank_mxu":
            stage_pagerank_mxu(int(sys.argv[3]), int(sys.argv[4]),
                               int(sys.argv[5]), sys.argv[6])
        elif stage == "semiring":
            stage_semiring(int(sys.argv[3]), int(sys.argv[4]),
                           int(sys.argv[5]), sys.argv[6])
        elif stage == "delta":
            stage_delta(int(sys.argv[3]), int(sys.argv[4]),
                        int(sys.argv[5]), sys.argv[6])
        elif stage == "tier":
            stage_tier(int(sys.argv[3]), int(sys.argv[4]),
                       int(sys.argv[5]), sys.argv[6])
        elif stage == "stream":
            stage_stream(int(sys.argv[3]), int(sys.argv[4]),
                         int(sys.argv[5]), sys.argv[6])
        elif stage == "latency":
            stage_latency(sys.argv[3])
        else:
            raise SystemExit(f"unknown stage {stage}")
    else:
        main()
